"""paddle_tpu.observability — serving telemetry (ISSUE 3 + ISSUE 5
tentpoles).

Dependency-free metrics + tracing + SLO + export for the inference
stack:

- :mod:`.metrics` — thread-safe :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` (fixed log-spaced latency buckets) behind a
  :class:`MetricsRegistry` with Prometheus text exposition and a
  JSON snapshot. Engines own a private registry by default;
  :func:`get_registry` is the process-wide instance.
- :mod:`.tracing` — :class:`RequestTrace`, the per-request lifecycle
  record every latency metric (TTFT / TPOT / queue wait / preemption
  cost) is derived from; carries a ``trace_id`` + failover hops across
  fleet workers and exports Chrome-trace events.
- :mod:`.slo` — declarative :class:`SLORule` objectives evaluated over
  sliding windows of registry snapshots by :class:`SLOEngine`
  (pending→firing→resolved with hysteresis, burn rate, deterministic
  ``check(now=)``).
- :mod:`.export` — :class:`TelemetryShipper`: bounded-queue periodic
  shipping of snapshots + trace summaries to pluggable sinks
  (:class:`JsonlFileSink`, :class:`HTTPPostSink`) with exponential
  backoff; never blocks or crashes the serving path.

The engine-step timeline rides the existing profiler: serving code
wraps admissions, prefills, decode chunks and evictions in
``profiler.RecordEvent(..., "engine")`` spans, so
``export_chrome_tracing`` renders one unified host timeline of request
lifecycle next to op-dispatch spans (PAPER §L0–L4 host+device merge).
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      DEFAULT_LATENCY_BUCKETS, get_registry,
                      merge_snapshots, now, quantile_from_buckets,
                      escape_help, escape_label)
from .tracing import (RequestTrace, LIFECYCLE_STATES, TERMINAL_STATES)
from .slo import SLORule, SLOEngine, AlertState
from .export import TelemetryShipper, JsonlFileSink, HTTPPostSink
from .flight import (FlightRecorder, build_bundle, dump_postmortem,
                     get_flight_recorder)
from .profiling import PHASES, StepProfiler, CompileTracker

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "get_registry", "merge_snapshots",
           "now", "quantile_from_buckets", "escape_help", "escape_label",
           "RequestTrace", "LIFECYCLE_STATES", "TERMINAL_STATES",
           "SLORule", "SLOEngine", "AlertState",
           "TelemetryShipper", "JsonlFileSink", "HTTPPostSink",
           "FlightRecorder", "build_bundle", "dump_postmortem",
           "get_flight_recorder",
           "PHASES", "StepProfiler", "CompileTracker"]
