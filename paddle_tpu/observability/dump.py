"""Postmortem dump CLI (ISSUE 13 satellite)::

    python -m paddle_tpu.observability.dump <dir> [reason]

Writes one postmortem bundle — the process-default flight recorder
(:func:`~paddle_tpu.observability.flight.get_flight_recorder`) plus
the process-default metrics registry — into ``<dir>`` and prints the
bundle path. Exit status: 0 on success, 1 when the dump failed, 2 on
usage errors. In-process tooling should call
:func:`~paddle_tpu.observability.flight.dump_postmortem` directly
(fleets pass their own recorder/registry/state)."""

from __future__ import annotations

import sys

USAGE = "usage: python -m paddle_tpu.observability.dump <dir> [reason]"


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if any(a in ("-h", "--help") for a in args):
        print(USAGE)
        return 0
    if not 1 <= len(args) <= 2:
        print(USAGE, file=sys.stderr)
        return 2
    reason = args[1] if len(args) > 1 else "manual"
    from .flight import dump_postmortem, get_flight_recorder
    from .metrics import get_registry
    path = dump_postmortem(args[0], reason=reason,
                           recorder=get_flight_recorder(),
                           registry=get_registry())
    if path is None:
        print("postmortem dump failed (see log)", file=sys.stderr)
        return 1
    print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
