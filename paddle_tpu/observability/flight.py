"""Flight recorder + postmortem bundles (ISSUE 13 tentpole; reference
shape: an aircraft FDR applied to the serving fleet — a bounded ring of
structured events that is ALWAYS on, cheap enough to never matter, and
harvested into a replayable artifact the moment something dies).

The r6–r15 stack's failure evidence was a cumulative metrics snapshot:
it says a worker restarted, never WHAT the fleet was doing in the steps
before. A :class:`FlightRecorder` closes that gap — lifecycle
transitions, preemptions, failovers, restarts, injected faults,
shed/quarantine decisions, compile events and step-phase outliers all
land in per-worker rings that mirror into one fleet ring, and
:func:`dump_postmortem` freezes the rings plus registry/scheduler/
allocator state into a JSON bundle. The fleet invokes it automatically
from the r9 watchdog ``on_stall``, the r14 restart harvest and poison
quarantine, so every chaos event leaves an artifact.

Determinism contract: the recorder takes an injected ``clock=``
(defaulting to the shared ``observability.now`` alias) and a
monotonically increasing sequence number; with an injected clock two
same-seed runs produce byte-identical bundles (``json.dump`` with
``sort_keys``), which the chaos suite pins. ``record`` is O(1), takes
one lock, and NEVER raises — observability must never take down
serving."""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque

from ..utils.log import get_logger, log_kv
from .metrics import now

__all__ = ["FlightRecorder", "build_bundle", "dump_postmortem",
           "get_flight_recorder", "BUNDLE_VERSION"]

_log = get_logger("paddle_tpu.observability.flight")

#: bundle schema version (bump on breaking layout changes; consumers
#: gate on it instead of sniffing keys)
BUNDLE_VERSION = 1


class FlightRecorder:
    """Bounded, lock-disciplined ring of structured events.

    - ``record(kind, **fields)`` appends ``{"seq", "t", "kind", ...}``
      — O(1), drop-oldest, exception-contained;
    - ``forward_to=`` mirrors every event into a parent recorder (the
      fleet ring) with a ``src`` tag, so worker rings stay local while
      the fleet keeps the global interleaving;
    - ``registry=`` registers fn-gauges (events seen / dropped) whose
      callbacks take the ring lock themselves — scrape threads read
      them outside any caller lock."""

    def __init__(self, capacity: int = 512, clock=None, name=None,
                 forward_to=None, registry=None):
        self.name = name
        self.capacity = int(capacity)
        self._clock = now if clock is None else clock
        self._forward = forward_to
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._seq = 0                                    # guarded-by: _lock
        self._dropped = 0                                # guarded-by: _lock
        if registry is not None:
            registry.gauge(
                "flight_events_seen",
                "events recorded into the flight ring since start",
                fn=self._seen)
            registry.gauge(
                "flight_events_dropped",
                "flight events evicted from the bounded ring",
                fn=self._drop_count)

    # fn-gauge callbacks run on the scrape thread with NO caller locks
    # held — they take the ring lock themselves
    def _seen(self) -> int:
        with self._lock:
            return self._seq

    def _drop_count(self) -> int:
        with self._lock:
            return self._dropped

    def record(self, kind: str, **fields):
        """Append one event; returns it (or None if recording failed).
        Never raises and never blocks beyond the ring lock."""
        try:
            t = float(self._clock())
            with self._lock:
                self._seq += 1
                evt = {"seq": self._seq, "t": round(t, 6),
                       "kind": str(kind)}
                evt.update(fields)
                if len(self._ring) == self._ring.maxlen:
                    self._dropped += 1
                self._ring.append(evt)
            if self._forward is not None:
                fwd = {k: v for k, v in fields.items() if k != "src"}
                self._forward.record(kind, src=self.name, **fwd)
            return evt
        except Exception as e:  # noqa: BLE001 — recorder never kills serving
            log_kv(_log, "flight_record_failed", level=logging.WARNING,
                   error=type(e).__name__, detail=str(e), kind=kind)
            return None

    def events(self, n=None, kind=None) -> list:
        """Newest-last copy of the ring; ``n`` keeps the newest n,
        ``kind`` filters."""
        with self._lock:
            evts = [dict(e) for e in self._ring]
        if kind is not None:
            evts = [e for e in evts if e.get("kind") == kind]
        return evts[-int(n):] if n else evts

    def snapshot(self) -> dict:
        """JSON-able view of the whole recorder (bundle component)."""
        with self._lock:
            return {"name": self.name, "capacity": self.capacity,
                    "seq": self._seq, "dropped": self._dropped,
                    "events": [dict(e) for e in self._ring]}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def __repr__(self):
        return (f"FlightRecorder({self.name!r}, "
                f"capacity={self.capacity})")


def build_bundle(reason="manual", recorder=None, registry=None,
                 traces=(), compile_log=(), config=None,
                 state=None) -> dict:
    """Assemble (but do not write) a postmortem bundle dict.

    Components mirror the ISSUE 13 schema: flight ring, registry
    snapshot, scheduler/allocator ``state``, last-N request trace
    summaries, compile log, config. Every component is optional so the
    same builder serves the fleet, a bare engine, and the CLI."""
    bundle = {"bundle_version": BUNDLE_VERSION, "reason": str(reason)}
    if recorder is not None:
        bundle["flight"] = recorder.snapshot()
    if registry is not None:
        bundle["metrics"] = registry.snapshot() \
            if hasattr(registry, "snapshot") else dict(registry)
    bundle["traces"] = [t.summary() if hasattr(t, "summary") else t
                        for t in traces]
    bundle["compile_log"] = list(compile_log)
    bundle["config"] = dict(config or {})
    bundle["state"] = dict(state or {})
    return bundle


def _write_bundle(path, bundle) -> None:  # staticcheck: io-boundary
    with open(path, "w", encoding="utf-8") as f:
        json.dump(bundle, f, sort_keys=True, indent=1, default=str)
        f.write("\n")


def _slug(s: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "-" for c in str(s))
    return out[:48] or "event"


def dump_postmortem(dirpath, reason="manual", recorder=None,
                    registry=None, traces=(), compile_log=(),
                    config=None, state=None, keep: int = 16):
    """Write one postmortem bundle into ``dirpath`` and return its
    path (None on failure — the dump must never take down serving).

    The file name is ``postmortem_<seq>_<reason>.json`` where ``seq``
    is the recorder's sequence number AFTER recording the dump itself
    as a ``postmortem`` event — monotone per recorder, so bundles from
    one run never collide and sort in event order. ``keep`` bounds the
    directory (oldest bundles beyond it are pruned)."""
    try:
        if recorder is not None:
            recorder.record("postmortem", reason=str(reason))
        bundle = build_bundle(reason=reason, recorder=recorder,
                              registry=registry, traces=traces,
                              compile_log=compile_log, config=config,
                              state=state)
        seq = bundle.get("flight", {}).get("seq", 0)
        os.makedirs(str(dirpath), exist_ok=True)
        path = os.path.join(
            str(dirpath), f"postmortem_{int(seq):06d}_{_slug(reason)}.json")
        _write_bundle(path, bundle)
        if keep:
            bundles = sorted(
                p for p in os.listdir(str(dirpath))
                if p.startswith("postmortem_") and p.endswith(".json"))
            for old in bundles[:-int(keep)]:
                os.remove(os.path.join(str(dirpath), old))
        log_kv(_log, "postmortem_dumped", level=logging.WARNING,
               path=path, reason=reason)
        return path
    except Exception as e:  # noqa: BLE001 — the dump is best-effort
        log_kv(_log, "postmortem_dump_failed", level=logging.ERROR,
               error=type(e).__name__, detail=str(e), reason=reason)
        return None


_DEFAULT: list = [None]
_DEFAULT_LOCK = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """Process-default recorder (ad-hoc tooling and the
    ``python -m paddle_tpu.observability.dump`` CLI). Fleets own
    PRIVATE recorders — pass ``recorder=get_flight_recorder()`` style
    wiring to share this one."""
    with _DEFAULT_LOCK:
        if _DEFAULT[0] is None:
            _DEFAULT[0] = FlightRecorder(name="process")
        return _DEFAULT[0]
