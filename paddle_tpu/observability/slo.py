"""Streaming SLO evaluation over registry snapshots (ISSUE 5 tentpole;
reference shape: Prometheus alerting rules — declarative objective,
``for:`` hold before firing, hysteresis on clear — evaluated here over
a sliding in-process window of :meth:`MetricsRegistry.snapshot` dicts
instead of a remote TSDB).

Why snapshots and not live metrics: counters and histogram buckets are
CUMULATIVE, so a windowed statistic is a delta between the snapshot
just outside the window and the newest one — p99-over-the-last-30s is
the quantile of the bucket-count DELTAS, an error rate is
Δfailed/Δadmitted. That makes evaluation pure: feed the same snapshots
and the same ``check(now=)`` timestamps and the state machine replays
deterministically (same discipline as the stall watchdog).

Burn rate follows the SRE-workbook convention: how fast the error
budget is being spent. For a quantile objective ``p99 < 0.5s`` the
budget is the tolerated tail mass (1 - 0.99); the measured bad
fraction over the window divided by that budget is the burn. A burn of
1.0 means exactly on budget; 10 means burning ten times too fast.
"""

from __future__ import annotations

import logging
from collections import deque
from dataclasses import dataclass, field

from ..utils.log import get_logger, log_kv
from .metrics import _parse_le, now, quantile_from_buckets

__all__ = ["SLORule", "SLOEngine", "AlertState"]

_log = get_logger("paddle_tpu.observability.slo")

_QUANTILE_STATS = {"p50": 0.5, "p90": 0.9, "p99": 0.99}


@dataclass(frozen=True)
class SLORule:
    """One declarative objective.

    ``stat`` selects the windowed statistic of ``metric``:

    - ``"p50"``/``"p90"``/``"p99"``: windowed quantile of a histogram
      (bucket-count deltas over the window);
    - ``"rate"``: Δcounter / Δt (per second);
    - ``"ratio"``: Δcounter / Δ(sum of ``total`` counters) — e.g.
      error rate = failed / (retired + failed);
    - ``"value"``: the newest gauge value (no window math).

    The objective HOLDS while ``stat(metric) op threshold`` is true;
    ``for_s`` is the breach hold before pending becomes firing and
    ``clear_for_s`` the hysteresis before firing resolves."""

    name: str
    metric: str
    stat: str
    threshold: float
    op: str = "<"
    window_s: float = 60.0
    for_s: float = 0.0
    clear_for_s: float = 0.0
    total: tuple = ()

    def __post_init__(self):
        if self.stat not in _QUANTILE_STATS and self.stat not in (
                "rate", "ratio", "value"):
            raise ValueError(f"SLORule {self.name}: unknown stat "
                             f"{self.stat!r}")
        if self.op not in ("<", "<=", ">", ">="):
            raise ValueError(f"SLORule {self.name}: unknown op "
                             f"{self.op!r}")
        if self.stat == "ratio" and not self.total:
            raise ValueError(f"SLORule {self.name}: ratio needs "
                             f"total= counters")

    def holds(self, measured: float) -> bool:
        if self.op == "<":
            return measured < self.threshold
        if self.op == "<=":
            return measured <= self.threshold
        if self.op == ">":
            return measured > self.threshold
        return measured >= self.threshold


@dataclass
class AlertState:
    """Per-rule alert lifecycle: ok -> pending -> firing -> ok."""

    rule: SLORule
    state: str = "ok"
    breach_since: float | None = None
    clear_since: float | None = None
    measured: float | None = None
    burn_rate: float | None = None
    fired_count: int = 0
    history: list = field(default_factory=list)


def _hist_delta(first: dict | None, last: dict | None):
    """Windowed histogram view: (delta cumulative buckets, delta count,
    observed max). ``first`` may be None (no pre-window baseline: the
    whole cumulative history is inside the window)."""
    if last is None:
        return None
    buckets = {k: float(c) for k, c in last["buckets"].items()}
    count = last["count"]
    if first is not None:
        for k, c in first["buckets"].items():
            buckets[k] = buckets.get(k, 0.0) - c
        count -= first["count"]
    return buckets, count, last.get("max")


def _bad_fraction(buckets: dict, total: float, threshold: float):
    """Fraction of windowed observations ABOVE ``threshold`` (first
    edge >= threshold bounds the below-count from the cumulative
    deltas)."""
    if total <= 0:
        return None
    below = 0.0
    for key in sorted(buckets, key=_parse_le):
        if _parse_le(key) >= threshold:
            below = buckets[key]
            break
    else:
        below = total
    return max(0.0, 1.0 - below / total)


class SLOEngine:
    """Sliding-window evaluator + alert state machine over a stream of
    registry snapshots.

    Feed it with :meth:`observe` (typically the fleet's merged
    snapshot once per step or scrape) and advance the state machines
    with :meth:`check`. Both take ``now=`` overrides so tests replay a
    scenario deterministically. ``on_alert`` is called with a dict on
    every firing and resolved transition — exceptions are contained
    (observability must never take down serving)."""

    def __init__(self, rules, on_alert=None, registry=None):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        self.on_alert = on_alert
        self._alerts = {r.name: AlertState(r) for r in self.rules}
        self._window: deque = deque()       # (t, snapshot)
        self._max_window = max((r.window_s for r in self.rules),
                               default=60.0)
        self.transitions: list[dict] = []
        self._fired = self._resolved = None
        self._firing_gauge = None
        if registry is not None:
            self._fired = registry.counter(
                "slo_alerts_fired_total", "SLO alerts that reached firing")
            self._resolved = registry.counter(
                "slo_alerts_resolved_total", "SLO alerts that resolved")
            self._firing_gauge = registry.gauge(
                "slo_alerts_firing", "currently firing SLO alerts",
                fn=lambda: len(self.firing()))

    # -- window -------------------------------------------------------------
    def observe(self, snapshot: dict, now_: float | None = None) -> None:
        t = now() if now_ is None else now_
        self._window.append((t, snapshot))
        self._prune(t)

    def _prune(self, t: float) -> None:
        # keep everything inside the widest window PLUS one older
        # snapshot as the delta baseline
        cutoff = t - self._max_window
        while (len(self._window) >= 2
               and self._window[1][0] <= cutoff):
            self._window.popleft()

    def _bounds(self, window_s: float, t: float):
        """(first, last) snapshots bracketing the window ending at
        ``t``: last = newest, first = newest snapshot at or before the
        window start (None if history starts inside the window)."""
        if not self._window:
            return None, None
        cutoff = t - window_s
        first = None
        for ts, snap in self._window:
            if ts <= cutoff:
                first = snap
            else:
                break
        return first, self._window[-1][1]

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, rule: SLORule, now_: float | None = None):
        """(measured, burn_rate) for one rule over its window; both
        None when the window holds no data (no-data = objective met)."""
        t = now() if now_ is None else now_
        first, last = self._bounds(rule.window_s, t)
        if last is None:
            return None, None
        if rule.stat in _QUANTILE_STATS:
            q = _QUANTILE_STATS[rule.stat]
            h0 = (first or {}).get("histograms", {}).get(rule.metric)
            h1 = last.get("histograms", {}).get(rule.metric)
            if h1 is None:
                return None, None
            buckets, total, mx = _hist_delta(h0, h1)
            # quantile of the bucket-count DELTAS (empty=None: no data
            # in the window means the objective is met, not breached)
            measured = quantile_from_buckets(q, buckets, total, mx,
                                             empty=None)
            if measured is None:
                return None, None
            budget = max(1.0 - q, 1e-12)
            bad = _bad_fraction(buckets, total, rule.threshold)
            burn = None if bad is None else bad / budget
            return measured, burn
        if rule.stat == "value":
            v = last.get("gauges", {}).get(rule.metric)
            if v is None or v != v:
                return None, None
            burn = (v / rule.threshold) if rule.threshold > 0 else None
            return v, burn

        def counter_delta(name):
            v1 = last.get("counters", {}).get(name)
            if v1 is None:
                return None
            v0 = (first or {}).get("counters", {}).get(name, 0.0)
            return v1 - v0

        d = counter_delta(rule.metric)
        if d is None:
            return None, None
        if rule.stat == "rate":
            if first is None and len(self._window) < 2:
                return None, None
            dt = rule.window_s
            measured = d / dt if dt > 0 else None
            if measured is None:
                return None, None
            burn = (measured / rule.threshold
                    if rule.threshold > 0 else None)
            return measured, burn
        # ratio
        denom = 0.0
        for name in rule.total:
            dd = counter_delta(name)
            if dd is not None:
                denom += dd
        if denom <= 0:
            return None, None
        measured = d / denom
        budget = rule.threshold if rule.threshold > 0 else 1e-12
        return measured, measured / budget

    # -- state machine ------------------------------------------------------
    def check(self, now_: float | None = None) -> list[dict]:
        """Advance every rule's alert state; returns the transitions
        that happened this check (firing / resolved dicts, also
        appended to :attr:`transitions` and sent to ``on_alert``)."""
        t = now() if now_ is None else now_
        events = []
        for rule in self.rules:
            st = self._alerts[rule.name]
            measured, burn = self.evaluate(rule, t)
            st.measured, st.burn_rate = measured, burn
            breach = (measured is not None
                      and not rule.holds(measured))
            if st.state == "ok":
                if breach:
                    st.state = "pending"
                    st.breach_since = t
            if st.state == "pending":
                if not breach:
                    st.state = "ok"
                    st.breach_since = None
                elif t - st.breach_since >= rule.for_s:
                    st.state = "firing"
                    st.clear_since = None
                    st.fired_count += 1
                    events.append(self._emit(st, "firing", t))
            elif st.state == "firing":
                if breach:
                    st.clear_since = None
                else:
                    if st.clear_since is None:
                        st.clear_since = t
                    if t - st.clear_since >= rule.clear_for_s:
                        st.state = "ok"
                        st.breach_since = st.clear_since = None
                        events.append(self._emit(st, "resolved", t))
        return events

    def _emit(self, st: AlertState, kind: str, t: float) -> dict:
        info = {"rule": st.rule.name, "state": kind, "t": t,
                "metric": st.rule.metric, "stat": st.rule.stat,
                "op": st.rule.op, "threshold": st.rule.threshold,
                "measured": st.measured, "burn_rate": st.burn_rate}
        st.history.append(info)
        self.transitions.append(info)
        if kind == "firing" and self._fired is not None:
            self._fired.inc()
        if kind == "resolved" and self._resolved is not None:
            self._resolved.inc()
        if self.on_alert is not None:
            try:
                self.on_alert(info)
            except Exception as e:  # noqa: BLE001 — never crash serving
                log_kv(_log, "on_alert_callback_failed",
                       level=logging.ERROR, rule=info.get("rule"),
                       error=type(e).__name__, detail=str(e))
        return info

    # -- views --------------------------------------------------------------
    def alert(self, name: str) -> AlertState:
        return self._alerts[name]

    def firing(self) -> list[str]:
        return [n for n, st in self._alerts.items()
                if st.state == "firing"]

    def states(self) -> dict:
        return {n: st.state for n, st in self._alerts.items()}
