"""Resilient off-host telemetry export (ISSUE 5 tentpole; reference
shape: the OpenTelemetry BatchSpanProcessor / Prometheus remote-write
contract — a bounded in-memory queue between the hot path and the
network, periodic flush, exponential backoff with jitter on sink
failure, and drop-oldest when the queue is full).

The invariant that matters: the serving path NEVER blocks and NEVER
sees a sink exception. ``enqueue`` is an O(1) deque append; the flush
either runs inline from ``tick()`` (fleet step loop) or on a daemon
thread; any sink failure is contained, counted, and backed off. The
shipper observes ITSELF in its own registry (enqueued / shipped /
dropped / retries / sink errors, queue depth, current backoff), so a
mis-behaving sink is visible in the same scrape as everything else.

Determinism: backoff jitter comes from a seeded ``random.Random`` and
``tick``/``flush`` take ``now=`` overrides, so failure scenarios
replay exactly in tests."""

from __future__ import annotations

import json
import logging
import random
import threading
import urllib.request
from collections import deque

from ..utils.log import get_logger, log_kv
from .metrics import MetricsRegistry, now

__all__ = ["TelemetryShipper", "JsonlFileSink", "HTTPPostSink"]

_log = get_logger("paddle_tpu.observability.export")


class JsonlFileSink:
    """Append each payload as one JSON line to a local file (the
    "off-host" part is whatever tails the file)."""

    def __init__(self, path):
        self.path = str(path)

    def emit(self, payload: dict) -> None:  # staticcheck: io-boundary
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(payload, default=str) + "\n")

    def __repr__(self):
        return f"JsonlFileSink({self.path!r})"


class HTTPPostSink:
    """POST each payload as JSON to a collector endpoint (stdlib
    urllib — no client stack dependency). Non-2xx raises, which the
    shipper turns into backoff + retry."""

    def __init__(self, url: str, timeout_s: float = 2.0):
        self.url = url
        self.timeout_s = timeout_s

    def emit(self, payload: dict) -> None:  # staticcheck: io-boundary
        data = json.dumps(payload, default=str).encode("utf-8")
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            if not 200 <= r.status < 300:
                raise OSError(f"HTTPPostSink: {self.url} -> {r.status}")

    def __repr__(self):
        return f"HTTPPostSink({self.url!r})"


class _SinkState:
    """Per-sink bounded queue + backoff bookkeeping (each sink fails
    independently: a dead HTTP collector must not stall the local
    JSONL file)."""

    __slots__ = ("sink", "queue", "failures", "next_ok_t", "backoff_s")

    def __init__(self, sink, queue_max: int):
        self.sink = sink
        self.queue: deque = deque(maxlen=queue_max)
        self.failures = 0
        self.next_ok_t = 0.0        # earliest time a retry may run
        self.backoff_s = 0.0


class TelemetryShipper:
    """Bounded-queue periodic shipper of telemetry payloads to
    pluggable sinks.

    - ``collect``: optional zero-arg callable returning the payload to
      ship each interval (e.g. the fleet's merged snapshot + retired
      trace summaries); ``enqueue`` pushes extra payloads directly.
    - each sink has its OWN bounded queue (``queue_max``, drop-oldest)
      and its own exponential backoff (``backoff_base_s`` doubling to
      ``backoff_max_s``, multiplied by ``1 + jitter*u`` with a seeded
      RNG).
    - drive it either with ``tick(now=)`` from an existing loop (the
      fleet calls this in ``step``) or with ``start()``/``stop()`` for
      a daemon flush thread.
    """

    def __init__(self, collect=None, sinks=(), interval_s: float = 5.0,
                 queue_max: int = 128, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 60.0, jitter: float = 0.1,
                 seed: int = 0, registry: MetricsRegistry | None = None):
        self.collect = collect
        self.interval_s = interval_s
        self.queue_max = queue_max
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._sinks = [_SinkState(s, queue_max) for s in sinks]
        self._lock = threading.Lock()
        self._last_flush_t = None
        self._thread = None
        self._stop = threading.Event()
        self.registry = (MetricsRegistry() if registry is None
                         else registry)
        r = self.registry
        self._enqueued = r.counter(
            "shipper_enqueued_total", "payloads accepted into queues")
        self._shipped = r.counter(
            "shipper_shipped_total", "payloads delivered to a sink")
        self._dropped = r.counter(
            "shipper_dropped_total",
            "payloads lost to full queues (drop-oldest)")
        self._retries = r.counter(
            "shipper_retries_total",
            "payload delivery attempts after a sink failure")
        self._errors = r.counter(
            "shipper_sink_errors_total", "sink emit() exceptions")
        r.gauge("shipper_queue_depth", "queued payloads across sinks",
                fn=self._depth)
        r.gauge("shipper_backoff_seconds",
                "max current per-sink backoff", fn=self._max_backoff)

    # -- self-observation ---------------------------------------------------
    def _depth(self) -> int:
        with self._lock:
            return sum(len(s.queue) for s in self._sinks)

    def _max_backoff(self) -> float:
        with self._lock:
            return max((s.backoff_s for s in self._sinks), default=0.0)

    def stats(self) -> dict:
        return {"enqueued": self._enqueued.value,
                "shipped": self._shipped.value,
                "dropped": self._dropped.value,
                "retries": self._retries.value,
                "sink_errors": self._errors.value,
                "queue_depth": self._depth()}

    # -- hot-path side ------------------------------------------------------
    def enqueue(self, payload: dict) -> None:
        """O(1), never blocks, never raises: full queues drop their
        OLDEST entry (freshest telemetry wins)."""
        with self._lock:
            for s in self._sinks:
                if len(s.queue) == s.queue.maxlen:
                    self._dropped.inc()
                s.queue.append(payload)
            if self._sinks:
                self._enqueued.inc()

    # -- flush side ---------------------------------------------------------
    def tick(self, now_: float | None = None) -> int:
        """Flush if ``interval_s`` elapsed since the last flush;
        returns payloads delivered. Safe to call every fleet step."""
        t = now() if now_ is None else now_
        if (self._last_flush_t is not None
                and t - self._last_flush_t < self.interval_s):
            return 0
        return self.flush(t)

    def flush(self, now_: float | None = None) -> int:
        """Collect (if configured), then drain every sink's queue,
        honoring per-sink backoff windows. All exceptions are
        contained."""
        t = now() if now_ is None else now_
        self._last_flush_t = t
        if self.collect is not None:
            try:
                payload = self.collect()
            except Exception as e:  # noqa: BLE001 — hot path stays alive
                log_kv(_log, "shipper_collect_failed",
                       level=logging.WARNING, error=type(e).__name__,
                       detail=str(e))
                payload = None
            if payload is not None:
                self.enqueue(payload)
        delivered = 0
        for s in self._sinks:
            if t < s.next_ok_t:
                continue                # still backing off
            while True:
                with self._lock:
                    if not s.queue:
                        break
                    payload = s.queue[0]
                    retry = s.failures > 0
                try:
                    s.sink.emit(payload)
                except Exception:   # noqa: BLE001 — contained
                    self._errors.inc()
                    if retry:
                        self._retries.inc()
                    s.failures += 1
                    base = min(
                        self.backoff_base_s * 2 ** (s.failures - 1),
                        self.backoff_max_s)
                    s.backoff_s = base * (
                        1.0 + self.jitter * self._rng.random())
                    s.next_ok_t = t + s.backoff_s
                    break           # keep payload queued for retry
                else:
                    if retry:
                        self._retries.inc()
                    s.failures = 0
                    s.backoff_s = 0.0
                    s.next_ok_t = 0.0
                    self._shipped.inc()
                    delivered += 1
                    with self._lock:
                        if s.queue and s.queue[0] is payload:
                            s.queue.popleft()
        return delivered

    # -- optional daemon thread ---------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.flush()
                except Exception as e:  # noqa: BLE001 — daemon never dies
                    log_kv(_log, "shipper_flush_failed",
                           level=logging.ERROR,
                           error=type(e).__name__, detail=str(e))

        self._thread = threading.Thread(
            target=_loop, name="telemetry-shipper", daemon=True)
        self._thread.start()

    def stop(self, final_flush: bool = True) -> None:
        if self._thread is None:
            if final_flush:
                self.flush()
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        if final_flush:
            self.flush()

    def close(self) -> dict:
        """Shutdown with a FINAL best-effort flush (ISSUE 9 satellite:
        ``stop(final_flush=False)`` silently lost everything still
        queued). Backoff windows are ignored — this is the last chance
        — but each sink gets ONE attempt per payload and is abandoned
        at its first failure (a dead sink must not stall shutdown).
        Whatever could not be delivered is counted dropped. Returns
        ``{"flushed": n, "dropped": n, "per_sink": {...}}`` and logs
        the same."""
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        flushed = 0
        dropped = 0
        per_sink: dict[str, dict] = {}
        for i, s in enumerate(self._sinks):
            key = f"{i}:{s.sink!r}"
            ok, lost = 0, 0
            while True:
                with self._lock:
                    if not s.queue:
                        break
                    payload = s.queue[0]
                try:
                    s.sink.emit(payload)
                except Exception as e:  # noqa: BLE001 — contained
                    self._errors.inc()
                    with self._lock:
                        lost = len(s.queue)
                        s.queue.clear()
                    for _ in range(lost):
                        self._dropped.inc()
                    log_kv(_log, "shipper_close_sink_failed",
                           level=logging.WARNING, sink=key,
                           error=type(e).__name__, detail=str(e),
                           dropped=lost)
                    break
                else:
                    self._shipped.inc()
                    ok += 1
                    with self._lock:
                        if s.queue and s.queue[0] is payload:
                            s.queue.popleft()
            flushed += ok
            dropped += lost
            per_sink[key] = {"flushed": ok, "dropped": lost}
        counts = {"flushed": flushed, "dropped": dropped,
                  "per_sink": per_sink}
        log_kv(_log, "shipper_closed", level=logging.INFO,
               flushed=flushed, dropped=dropped)
        return counts
