"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability surface of the PaddlePaddle
reference (see SURVEY.md): eager autograd + jit compilation, full nn/optim/io
stacks, and hybrid-parallel training (DP/TP/PP/SP/EP/ZeRO) — built
TPU-first on JAX/XLA/Pallas: ops are pure-jax functions XLA fuses onto the
MXU, autograd is jax.vjp over those functions, distribution is GSPMD over a
jax.sharding.Mesh, and the hot kernels (flash attention, MoE dispatch) are
Pallas.
"""

from __future__ import annotations

import os as _os

# Multi-process rendezvous must happen before ANY backend-initializing jax
# call (jax.distributed.initialize's own requirement), and importing this
# package touches the backend — so when the launch CLI has wired the env
# (reference launch/controllers/collective.py), connect right here.
if int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1 and (
        _os.environ.get("PADDLE_MASTER")):
    import jax as _jax
    from jax._src import distributed as _jd
    if _jd.global_state.client is None:  # raw-jax workers may have connected
        _jax.distributed.initialize(
            coordinator_address=_os.environ["PADDLE_MASTER"],
            num_processes=int(_os.environ["PADDLE_TRAINERS_NUM"]),
            process_id=int(_os.environ.get("PADDLE_TRAINER_ID", "0")))

from . import flags  # noqa: F401  (registers core flags first)
from .flags import set_flags, get_flags  # noqa: F401

from .core.dtype import (  # noqa: F401
    dtype, float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, get_default_dtype, set_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401

from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all
from .ops.random import seed, get_rng_state, set_rng_state  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import metric  # noqa: F401
from . import device  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import framework  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import inference  # noqa: F401
from . import sparse  # noqa: F401
from . import geometric  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import incubate  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import static  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from . import callbacks  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu  # noqa: F401
from .metric import accuracy  # noqa: F401

__version__ = "0.1.0"

__all__ = (
    ["Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad", "grad",
     "seed", "save", "load", "set_default_dtype", "get_default_dtype",
     "set_flags", "get_flags", "set_device", "get_device", "ParamAttr",
     "Model", "summary",
     "accuracy"]
    + list(_ops_all)
)
