"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability surface of the PaddlePaddle
reference (see SURVEY.md): eager autograd + jit compilation, full nn/optim/io
stacks, and hybrid-parallel training (DP/TP/PP/SP/EP/ZeRO) — built
TPU-first on JAX/XLA/Pallas: ops are pure-jax functions XLA fuses onto the
MXU, autograd is jax.vjp over those functions, distribution is GSPMD over a
jax.sharding.Mesh, and the hot kernels (flash attention, MoE dispatch) are
Pallas.
"""

from __future__ import annotations

import os as _os

# Multi-process rendezvous must happen before ANY backend-initializing jax
# call (jax.distributed.initialize's own requirement), and importing this
# package touches the backend — so when the launch CLI has wired the env
# (reference launch/controllers/collective.py), connect right here.
if int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1 and (
        _os.environ.get("PADDLE_MASTER")):
    import jax as _jax
    from jax._src import distributed as _jd
    if _jd.global_state.client is None:  # raw-jax workers may have connected
        _jax.distributed.initialize(
            coordinator_address=_os.environ["PADDLE_MASTER"],
            num_processes=int(_os.environ["PADDLE_TRAINERS_NUM"]),
            process_id=int(_os.environ.get("PADDLE_TRAINER_ID", "0")))

from . import flags  # noqa: F401  (registers core flags first)
from .flags import set_flags, get_flags  # noqa: F401

from .core.dtype import (  # noqa: F401
    dtype, float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, get_default_dtype, set_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401

from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all
from .ops.random import seed, get_rng_state, set_rng_state  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import metric  # noqa: F401
from . import device  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import framework  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import inference  # noqa: F401
from . import sparse  # noqa: F401
from . import geometric  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import incubate  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import static  # noqa: F401
from . import signal  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from . import reader  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from . import callbacks  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu  # noqa: F401
from .metric import accuracy  # noqa: F401
from .framework.core import (  # noqa: F401
    finfo, iinfo, set_printoptions, CPUPlace, CUDAPlace, CUDAPinnedPlace,
    TPUPlace, XPUPlace, CustomPlace, in_dynamic_mode, in_dygraph_mode,
    enable_static, disable_static, create_parameter, LazyGuard,
    disable_signal_handler, is_complex, is_floating_point, is_integer,
    is_tensor, flops,
)

from .distributed.parallel import DataParallel  # noqa: F401

# dtype alias shadowing the builtin, as the reference does (paddle.bool)
globals()["bool"] = bool_


def batch(reader, batch_size, drop_last=False):
    """Legacy reader-decorator batching (reference: python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def check_shape(shape, op_name="", expected_shape_type=(list, tuple),
                expected_element_type=(int,), expected_tensor_dtype=None):
    """Shape-argument validation (reference: base/data_feeder.py:212).
    Dygraph skips checks like the reference; static scripts get the type
    errors."""
    if in_dynamic_mode():
        return
    if not isinstance(shape, expected_shape_type):
        raise TypeError(f"The shape of '{op_name}' must be "
                        f"{expected_shape_type}, got {type(shape)}")
    for item in shape:
        if not isinstance(item, expected_element_type):
            raise TypeError(f"element of shape in '{op_name}' must be "
                            f"{expected_element_type}, got {type(item)}")


def get_cuda_rng_state():
    """Device RNG state (reference: paddle.get_cuda_rng_state; on TPU the
    accelerator RNG is the same counter-based generator)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


# remaining reference tensor methods living outside the op surface
# (tensor/__init__.py lists them in tensor_method_func too)
from .framework.core import (  # noqa: E402
    is_complex as _isc, is_floating_point as _isf, is_integer as _isi,
    create_parameter as _cp)
from .signal import stft as _stft, istft as _istft  # noqa: E402
from .ops.linalg import inverse as _inverse  # noqa: E402

for _name, _fn in [("is_complex", _isc), ("is_floating_point", _isf),
                   ("is_integer", _isi), ("create_parameter",
                                          staticmethod(_cp)),
                   ("stft", _stft), ("istft", _istft),
                   ("inverse", _inverse)]:
    if not hasattr(Tensor, _name):
        setattr(Tensor, _name, _fn)


def create_tensor(dtype, name=None, persistable=False):
    """reference tensor/creation.py create_tensor — an empty typed
    tensor slot."""
    import jax.numpy as _jnp
    from .core.dtype import convert_dtype
    t = Tensor(_jnp.zeros((), convert_dtype(dtype)), name=name)
    t.persistable = persistable
    return t


Tensor.create_tensor = staticmethod(create_tensor)

__version__ = "0.1.0"

__all__ = (
    ["Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad", "grad",
     "seed", "save", "load", "set_default_dtype", "get_default_dtype",
     "set_flags", "get_flags", "set_device", "get_device", "ParamAttr",
     "Model", "summary", "accuracy",
     "finfo", "iinfo", "set_printoptions", "CPUPlace", "CUDAPlace",
     "CUDAPinnedPlace", "TPUPlace", "in_dynamic_mode", "in_dygraph_mode",
     "enable_static", "disable_static", "create_parameter", "LazyGuard",
     "disable_signal_handler", "is_complex", "is_floating_point",
     "is_integer", "is_tensor", "flops"]
    + list(_ops_all)
)
