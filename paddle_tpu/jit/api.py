"""paddle_tpu.jit — dynamic-to-static compilation
(reference: python/paddle/jit/api.py:242 to_static; SOT bytecode tracer in
jit/sot/; AST path in jit/dy2static/).

TPU-native design: the reference needs a bytecode/AST tracer because its ops
execute eagerly in C++; here every op is a jax-traceable function, so
"to_static" is direct jax tracing of the SAME eager code — the Tensor tape
runs at trace time and whole programs (including backward + optimizer
update, see TrainStep) lower to one XLA executable. Guards/graph-breaks
(SOT's job) reduce to jax.jit's shape/dtype-keyed compile cache.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..ops import random as R

__all__ = ["to_static", "not_to_static", "ignore_module", "enable_to_static",
           "TrainStep", "InputSpec", "StaticFunction"]

_to_static_enabled = [True]


def enable_to_static(flag: bool):
    _to_static_enabled[0] = bool(flag)


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _collect_state(fn) -> list[tuple[str, Tensor]]:
    """Find the Layer state captured by fn (Layer itself, bound method, or
    attribute `self` on a callable)."""
    from ..nn.layer.layers import Layer
    owner = None
    if isinstance(fn, Layer):
        owner = fn
    elif hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
        owner = fn.__self__
    if owner is None:
        return []
    return list(owner.state_dict().items())


class StaticFunction:
    """Callable wrapping jax.jit over the eager code
    (reference program_translator.py:316 StaticFunction)."""

    def __init__(self, function: Callable, input_spec=None, full_graph=False,
                 advance_rng=True, **kwargs):
        """``advance_rng=False``: trace with a FIXED key instead of
        consuming the global generator per call — for no-grad eval
        forwards whose callers must not perturb the shared random
        stream (hapi jit eval)."""
        self._raw_fn = function
        self._advance_rng = advance_rng
        from ..nn.layer.layers import Layer
        self._layer = function if isinstance(function, Layer) else None
        # capture the ORIGINAL forward now: to_static may later rebind
        # layer.forward to the compiled path
        self._callable = (function.forward if self._layer is not None
                          else function)
        self._input_spec = input_spec
        self._jitted = None
        self._state_items: list[tuple[str, Tensor]] = []
        # graph-break bookkeeping (reference SOT guard/retrace:
        # jit/sot/opcode_translator/executor/guard.py): jax.jit's
        # shape/dtype-keyed cache IS the guard — a changed input signature
        # retraces (see _trace_count); full_graph=False additionally arms
        # the eager fallback for non-traceable Python
        self._full_graph = full_graph
        self._fallback = False      # broke once: route through mixed mode
        self._eager = False         # mixed mode also failed: plain eager
        self._mixed_engine = None
        self._trace_count = 0
        functools.update_wrapper(self, self._callable)

    def _build(self):
        self._state_items = _collect_state(
            self._layer if self._layer is not None else self._raw_fn)
        state_objs = [t for _, t in self._state_items]

        def pure(state_vals, rng_key, args, kwargs):
            self._trace_count += 1  # python side effect: runs at trace time
            originals = [t._value for t in state_objs]
            orig_nodes = [(t._grad_node, t._out_index) for t in state_objs]
            old_key = R.default_generator._key
            try:
                for t, v in zip(state_objs, state_vals):
                    t._value = v
                    t._grad_node = None
                R.default_generator._key = rng_key
                out = self._callable(*args, **kwargs)
                out_vals = jax.tree_util.tree_map(
                    lambda x: x._value if isinstance(x, Tensor) else x, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                new_state = [t._value for t in state_objs]
                return out_vals, new_state
            finally:
                for t, v, (n, i) in zip(state_objs, originals, orig_nodes):
                    t._value = v
                    t._grad_node = n
                    t._out_index = i
                R.default_generator._key = old_key

        self._pure = pure
        self._jitted = jax.jit(pure)

    def export(self, example_args):
        """jax.export the forward (state baked as inputs) to a serialized
        StableHLO artifact — the jit.save deployment path (reference
        jit/api.py save); returns the jax.export.Exported object."""
        import jax.export
        if self._jitted is None:
            self._build()
        state_vals = [t._value for _, t in self._state_items]
        pure = self._pure

        def fwd(state_vals, xs):
            out, _ = pure(state_vals, jax.random.PRNGKey(0), tuple(xs), {})
            return out

        return jax.export.export(jax.jit(fwd))(
            state_vals, [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                         for a in example_args])

    def _call_mixed(self, *args, **kwargs):
        """Mixed-mode execution after a graph break (core/lazy.py): the
        function's Python runs natively while grad-free op chains
        accumulate into cached compiled segments, flushed at each host
        read. Any failure demotes permanently to plain eager."""
        from ..core import lazy
        if self._mixed_engine is None:
            self._mixed_engine = lazy.SegmentEngine()
        eng = self._mixed_engine
        # snapshot layer state so a failed capture can be rolled back and
        # re-run eagerly WITHOUT double-applying buffer mutations (BN
        # running stats etc.)
        snapshot = [(t, t._value, t._version) for _, t in self._state_items]
        failure = None
        lazy.activate(eng)
        try:
            out = self._callable(*args, **kwargs)
            eng.flush()
        except Exception as e:  # noqa: BLE001 — any break demotes to eager
            failure = e
            eng.abort()         # pending placeholders can't materialize
        finally:
            lazy.deactivate(eng)
        if failure is not None:
            for t, v, ver in snapshot:
                t._value = v
                t._version = ver
            import warnings
            warnings.warn(
                f"to_static: mixed-mode capture of "
                f"{getattr(self._callable, '__name__', '?')} failed "
                f"({type(failure).__name__}: {failure}); falling back to "
                f"eager execution for this function.",
                RuntimeWarning, stacklevel=2)
            self._eager = True
            return self._callable(*args, **kwargs)
        # layer buffers mutated mid-call (BN stats) hold flushed lazies
        for _, t in self._state_items:
            if isinstance(t._value, lazy.LazyValue):
                t._value = t._value.force()

        def _force(x):
            if isinstance(x, Tensor) and isinstance(x._value,
                                                    lazy.LazyValue):
                x._value = x._value.force()
            return x

        return jax.tree_util.tree_map(
            _force, out, is_leaf=lambda x: isinstance(x, Tensor))

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled[0] or self._eager:
            return self._callable(*args, **kwargs)
        if self._fallback:
            return self._call_mixed(*args, **kwargs)
        if self._jitted is None:
            self._build()
        from ..core.lazy import concrete as _conc
        state_objs = [t for _, t in self._state_items]
        state_vals = [_conc(t._value) for t in state_objs]
        args_vals = jax.tree_util.tree_map(
            lambda x: _conc(x._value) if isinstance(x, Tensor) else x, args,
            is_leaf=lambda x: isinstance(x, Tensor))
        kwargs_vals = jax.tree_util.tree_map(
            lambda x: _conc(x._value) if isinstance(x, Tensor) else x, kwargs,
            is_leaf=lambda x: isinstance(x, Tensor))
        key = R.next_key() if self._advance_rng else jax.random.PRNGKey(0)
        try:
            out_vals, new_state = self._jitted(state_vals, key,
                                               args_vals, kwargs_vals)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError) as e:
            # graph break: non-traceable Python (data-dependent control
            # flow, host round trips). The reference's SOT executes traced
            # subgraphs between breaks (opcode_executor.py); the TPU
            # analogue is mixed-mode capture (core/lazy.py) — compiled
            # segments stitched around the function's own host Python.
            if self._full_graph:
                raise
            import warnings
            warnings.warn(
                f"to_static: {getattr(self._callable, '__name__', '?')} is "
                f"not fully traceable ({type(e).__name__}); switching to "
                f"mixed-mode capture (compiled subgraphs around the host-"
                f"dependent Python). Use static-safe control flow "
                f"(paddle.static.nn.cond / lax.cond) to keep the whole "
                f"function in one program.", RuntimeWarning, stacklevel=2)
            self._fallback = True
            return self._call_mixed(*args, **kwargs)
        # buffer updates (e.g. BN running stats) land back in the objects
        for t, v in zip(state_objs, new_state):
            t._value = v
        return jax.tree_util.tree_map(
            lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out_vals)

    # paddle API surface
    def concrete_program(self):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(
            self._callable.__func__ if hasattr(self._callable, "__func__")
            else self._callable)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """reference jit/api.py:242. Decorator or call-style."""

    def decorate(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec, **kwargs)
            fn.forward_static = static
            # wrap the layer: calling it goes through the compiled path
            def compiled_call(*a, **k):
                return static(*a, **k)
            fn.forward = compiled_call
            fn._static_function = static
            return fn
        return StaticFunction(fn, input_spec, **kwargs)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules: Sequence[Any]):
    pass


class TrainStep:
    """Whole-train-step compilation: forward + backward + optimizer update in
    ONE XLA executable with donated buffers.

    This is the TPU answer to the reference's Program+Executor hot path
    (SURVEY §3.3): zero per-op Python overhead in steady state.

        step = TrainStep(model, opt, loss_fn)
        loss = step(x, y)          # compiled after first call
    """

    def __init__(self, model, optimizer, loss_fn: Callable,
                 has_aux: bool = False):
        """``has_aux``: loss_fn returns (loss, aux_pytree_of_Tensors);
        the step returns (loss, aux) with aux materialized — lets
        callers (hapi metrics) get batch outputs from the SAME compiled
        program instead of a second forward."""
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.has_aux = has_aux
        self._jitted = None
        self._params: list[Parameter] = []
        self._buffers: list[Tensor] = []

    def _build(self):
        self.optimizer._ensure_state()
        self._params = [p for p in self.optimizer._parameter_list]
        state = dict(self.model.state_dict())
        param_ids = {id(p) for p in self._params}
        self._buffers = [t for t in state.values() if id(t) not in param_ids]
        opt = self.optimizer

        def pure(param_vals, buffer_vals, opt_state, rng_key, step_count,
                 lr, args):
            originals = [(t, t._value, t._grad_node, t._out_index, t.grad)
                         for t in self._params + self._buffers]
            old_key = R.default_generator._key
            old_acc = {k: list(v) for k, v in opt._accumulators.items()}
            old_step = opt._global_step
            old_fns = dict(opt._update_fns)
            opt.get_lr = lambda: lr  # traced lr (scheduler-safe)
            try:
                for t, v in zip(self._params, param_vals):
                    t._value = v
                    t._grad_node = None
                    t.grad = None
                for t, v in zip(self._buffers, buffer_vals):
                    t._value = v
                    t._grad_node = None
                R.default_generator._key = rng_key
                for slot in opt._accumulators:
                    opt._accumulators[slot] = list(opt_state[slot])
                opt._global_step = step_count
                res = self.loss_fn(self.model, *args)
                loss, aux = res if self.has_aux else (res, None)
                loss.backward()
                opt.step()
                new_params = [t._value for t in self._params]
                new_buffers = [t._value for t in self._buffers]
                new_opt = {k: list(v) for k, v in opt._accumulators.items()}
                aux_vals = jax.tree_util.tree_map(
                    lambda x: x._value if isinstance(x, Tensor) else x,
                    aux, is_leaf=lambda x: isinstance(x, Tensor))
                return loss._value, aux_vals, new_params, new_buffers, \
                    new_opt
            finally:
                for t, v, n, i, g in originals:
                    t._value = v
                    t._grad_node = n
                    t._out_index = i
                    t.grad = g
                opt._accumulators = old_acc
                opt._global_step = old_step
                opt._update_fns = old_fns
                del opt.get_lr  # restore class method
                R.default_generator._key = old_key

        self._jitted = jax.jit(pure, donate_argnums=(0, 2))

    def __call__(self, *args):
        if self._jitted is None:
            self._build()
        opt = self.optimizer
        from ..core.lazy import concrete as _conc
        param_vals = [p._value for p in self._params]
        buffer_vals = [b._value for b in self._buffers]
        opt_state = {k: list(v) for k, v in opt._accumulators.items()}
        args_vals = jax.tree_util.tree_map(
            lambda x: _conc(x._value) if isinstance(x, Tensor) else
            (jnp.asarray(x) if isinstance(x, np.ndarray) else x), args,
            is_leaf=lambda x: isinstance(x, (Tensor, np.ndarray)))
        from ..device import oom_diagnostics
        with oom_diagnostics(self.model, opt):
            loss_val, aux_vals, new_params, new_buffers, new_opt = \
                self._jitted(
                    param_vals, buffer_vals, opt_state, R.next_key(),
                    jnp.asarray(opt._global_step, jnp.int32),
                    jnp.asarray(opt.get_lr(), jnp.float32), args_vals)
        for p, v in zip(self._params, new_params):
            p._value = v
        for b, v in zip(self._buffers, new_buffers):
            b._value = v
        for k in opt._accumulators:
            opt._accumulators[k] = list(new_opt[k])
        opt._global_step += 1
        if opt._lr_scheduler is not None:
            pass  # user steps the scheduler explicitly, as in the reference
        if self.has_aux:
            aux = jax.tree_util.tree_map(
                lambda x: Tensor(x) if isinstance(x, jax.Array) else x,
                aux_vals)
            return Tensor(loss_val), aux
        return Tensor(loss_val)
