"""jit.save / jit.load (reference: python/paddle/jit/api.py save/load →
serialized inference program + params; C++ runtime paddle/fluid/jit/).

TPU-native: the deployable artifact is params + a jax.export StableHLO
module when exportable; fallback stores params + the layer's pickled config
for python-side reload (serving path in paddle_tpu.inference uses the
compiled executable cache directly)."""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Saves state_dict + (if possible) a StableHLO export of forward."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__name__}
    payload = {"state": state, "meta": meta}
    stablehlo = None
    input_meta = None
    if input_spec:
        import warnings

        import jax.numpy as jnp

        from .api import StaticFunction
        from ..core.dtype import convert_dtype
        try:
            sf = layer._static_function \
                if hasattr(layer, "_static_function") \
                else StaticFunction(layer)
            examples = [Tensor(jnp.zeros(
                [d if d is not None and d > 0 else 1 for d in spec.shape],
                convert_dtype(spec.dtype))) for spec in input_spec]
            exported = sf.export(examples)
            stablehlo = exported.serialize()
            input_meta = [{"shape": list(spec.shape),
                           "dtype": str(spec.dtype),
                           "name": spec.name or f"x{i}"}
                          for i, spec in enumerate(input_spec)]
        except Exception as e:  # noqa: BLE001 — params still saved
            warnings.warn(
                f"StableHLO export failed ({type(e).__name__}: {e}); "
                f"artifact carries params only", RuntimeWarning)
    payload["stablehlo"] = stablehlo
    payload["input_meta"] = input_meta
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(payload, f, protocol=4)
    return payload  # callers (onnx bridge) read metadata without a
    #                 second full deserialization of the weights


class TranslatedLayer:
    """Loaded inference artifact (reference jit TranslatedLayer)."""

    def __init__(self, payload):
        self._state = payload["state"]
        self._stablehlo = payload.get("stablehlo")
        self.input_meta = payload.get("input_meta")
        self._rebuilt = None
        if self._stablehlo is not None:
            import jax.export
            self._rebuilt = jax.export.deserialize(self._stablehlo)

    def state_dict(self):
        import jax.numpy as jnp
        return {k: Tensor(jnp.asarray(v)) for k, v in self._state.items()}

    def __call__(self, *args):
        if self._rebuilt is None:
            raise RuntimeError(
                "this artifact has no compiled program; load its state_dict "
                "into the model class instead")
        import jax.numpy as jnp
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        state_vals = [jnp.asarray(v) for v in self._state.values()]
        out = self._rebuilt.call(state_vals, vals)
        import jax
        return jax.tree_util.tree_map(Tensor, out)

    def eval(self):
        return self

    def train(self):
        return self


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload)
