"""paddle_tpu.jit (reference: python/paddle/jit/__init__.py)."""

from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, enable_to_static, TrainStep,
    InputSpec, StaticFunction,
)
from .save_load import save, load, TranslatedLayer  # noqa: F401

__all__ = ["to_static", "not_to_static", "ignore_module", "enable_to_static",
           "TrainStep", "InputSpec", "StaticFunction", "save", "load"]
