"""paddle_tpu.jit (reference: python/paddle/jit/__init__.py)."""

from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, enable_to_static, TrainStep,
    InputSpec, StaticFunction,
)
from .save_load import save, load, TranslatedLayer  # noqa: F401

__all__ = ["to_static", "not_to_static", "ignore_module", "enable_to_static",
           "TrainStep", "InputSpec", "StaticFunction", "save", "load"]


_code_level = 0
_verbosity = 0


def set_code_level(level=100, also_to_stdout=False):
    """Log transformed code at the given level (reference: jit/dy2static
    set_code_level). Our tracer has no AST rewriting stage, so this sets
    jax's jaxpr logging verbosity knob instead."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    """reference jit set_verbosity — controls transform logging."""
    global _verbosity
    _verbosity = level
    import logging
    logging.getLogger("jax").setLevel(
        logging.DEBUG if level >= 3 else logging.WARNING)


__all__ += ["set_code_level", "set_verbosity"]
