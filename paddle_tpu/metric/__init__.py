"""Metrics (reference: python/paddle/metric/metrics.py:34 Metric ABC +
Accuracy/Precision/Recall/Auc)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        self._name = self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = order == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        res = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += c.shape[0]
            res.append(num / c.shape[0])
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label)
    if lab.ndim == 2 and lab.shape[1] == 1:
        lab = lab[:, 0]
    order = np.argsort(-pred, axis=-1)[:, :k]
    c = (order == lab[:, None]).any(axis=1)
    import jax.numpy as jnp
    return Tensor(jnp.asarray(c.mean(dtype=np.float32)))


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.round(p * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoidal over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr))
