"""paddle_tpu.fft — discrete Fourier transforms (reference:
python/paddle/fft.py, ~30 functions over pocketfft).

Lowering: jnp.fft (XLA native). The XLA TPU backend supports neither FFT
nor the complex dtype at all (UNIMPLEMENTED), so under a TPU default
backend every transform hops to the host CPU device via an in-graph
jax.device_put — the same shape as the reference's CPU pocketfft path —
and gradients flow back through the transfer. The private ``_dft*``
helpers implement the transform as real matmuls on the MXU (complex
arithmetic decomposed into 4 real GEMMs); they are the TPU-side building
block for real-valued pipelines (audio spectrograms) that never need a
complex array, and are parity-tested on CPU. Norm semantics match
numpy/the reference: "backward" (default), "ortho", "forward".
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.dispatch import defop
from .core.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftshift", "ifftshift", "fftfreq", "rfftfreq"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _host_call(jfn, x, **kw):
    """On TPU (no complex support) compute on the host CPU device — inputs
    device_put to CPU and the call run under jax.default_device(cpu) so
    jnp.fft's internal scalars also land there; the transfer is in-graph
    so vjp moves grads back automatically."""
    if jax.default_backend() == "tpu":
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return jfn(jax.device_put(x, cpu), **kw)
    return jfn(x, **kw)


# ---------------------------------------------------------------------------
# DFT-as-matmul core (TPU path)
# ---------------------------------------------------------------------------
def _dft_mats(n, inverse, dtype):
    j = jnp.arange(n, dtype=dtype)
    ang = (2.0 * jnp.pi / n) * jnp.outer(j, j)
    sgn = 1.0 if inverse else -1.0
    return jnp.cos(ang), sgn * jnp.sin(ang)        # W = Wr + i·Wi


def _split(x):
    if jnp.iscomplexobj(x):
        return jnp.real(x), jnp.imag(x)
    return x, None


def _resize_axis(x, n, axis):
    cur = x.shape[axis]
    if n is None or n == cur:
        return x
    if n < cur:
        return jax.lax.slice_in_dim(x, 0, n, axis=axis)
    pads = [(0, 0, 0)] * x.ndim
    pads[axis] = (0, n - cur, 0)
    return jax.lax.pad(x, jnp.zeros((), x.dtype), pads)


def _norm_scale(n, norm, inverse):
    if norm == "ortho":
        return 1.0 / jnp.sqrt(jnp.asarray(float(n)))
    if (norm == "forward") != inverse:
        # forward-norm fft or backward-norm ifft carries the 1/n
        return 1.0 / n
    return 1.0


def _dft1d(x, n, axis, norm, inverse):
    """Full complex DFT along ``axis`` via real matmuls."""
    x = _resize_axis(x, n, axis) if n is not None else x
    n = x.shape[axis]
    rdt = jnp.finfo(x.dtype).dtype if jnp.issubdtype(
        x.dtype, jnp.floating) else jnp.float32
    if jnp.iscomplexobj(x):
        rdt = jnp.real(x).dtype
    Wr, Wi = _dft_mats(n, inverse, rdt)
    xm = jnp.moveaxis(x, axis, -1)
    xr, xi = _split(xm)
    yr = xr @ Wr - (xi @ Wi if xi is not None else 0.0)
    yi = xr @ Wi + (xi @ Wr if xi is not None else 0.0)
    s = _norm_scale(n, norm, inverse)
    out = jax.lax.complex(yr * s, yi * s)
    return jnp.moveaxis(out, -1, axis)


def _dft_rfft(x, n, axis, norm):
    full = _dft1d(x, n, axis, norm, inverse=False)
    m = full.shape[axis] // 2 + 1
    return jax.lax.slice_in_dim(full, 0, m, axis=axis)


def _dft_irfft(x, n, axis, norm):
    m = x.shape[axis]
    n = n if n is not None else 2 * (m - 1)
    # rebuild the hermitian spectrum: full[:m] = x, full[n-k] = conj(x[k])
    x = _resize_axis(x, n // 2 + 1, axis)
    body = jax.lax.slice_in_dim(x, 1, (n + 1) // 2, axis=axis)
    tail = jnp.conj(jnp.flip(body, axis=axis))
    full = jnp.concatenate([x, tail], axis=axis)
    out = _dft1d(full, None, axis, norm, inverse=True)
    return jnp.real(out)


# ---------------------------------------------------------------------------
# op builders — jnp.fft, hopped to the host CPU device under a TPU backend
# ---------------------------------------------------------------------------
def _fft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.fft, x, n=n, axis=axis, norm=norm)


def _ifft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.ifft, x, n=n, axis=axis, norm=norm)


def _rfft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.rfft, x, n=n, axis=axis, norm=norm)


def _irfft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.irfft, x, n=n, axis=axis, norm=norm)


def _hfft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.hfft, x, n=n, axis=axis, norm=norm)


def _ihfft_raw(x, n, axis, norm):
    return _host_call(jnp.fft.ihfft, x, n=n, axis=axis, norm=norm)


def _fftn_raw(x, s, axes, norm, inverse, real_last=None):
    """n-d DFT via per-axis matmul transforms (TPU-side real building
    block; see module docstring)."""
    if axes is None:
        axes = tuple(range(x.ndim)) if s is None else \
            tuple(range(x.ndim - len(s), x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    s = list(s) if s is not None else [None] * len(axes)
    if real_last == "rfft":
        # rfft on the last listed axis, complex fft on the rest
        x = _dft_rfft(x, s[-1], axes[-1], norm)
        for a, nn in zip(axes[:-1], s[:-1]):
            x = _dft1d(x, nn, a, norm, inverse=False)
        return x
    if real_last == "irfft":
        for a, nn in zip(axes[:-1], s[:-1]):
            x = _dft1d(x, nn, a, norm, inverse=True)
        return _dft_irfft(x, s[-1], axes[-1], norm)
    for a, nn in zip(axes, s):
        x = _dft1d(x, nn, a, norm, inverse)
    return x


def _mk1d(name, raw):
    @defop(name)
    def _op(x, n=None, axis=-1, norm="backward"):
        return raw(x, n, axis, norm)

    def api(x, n=None, axis=-1, norm="backward", name=None):
        return _op(_t(x), n=n, axis=axis, norm=norm)
    api.__name__ = name
    api.__doc__ = f"reference python/paddle/fft.py {name}."
    return api


fft = _mk1d("fft", _fft_raw)
ifft = _mk1d("ifft", _ifft_raw)
rfft = _mk1d("rfft", _rfft_raw)
irfft = _mk1d("irfft", _irfft_raw)
hfft = _mk1d("hfft", _hfft_raw)
ihfft = _mk1d("ihfft", _ihfft_raw)


def _mknd(name, default_axes=None):
    @defop(name)
    def _op(x, s=None, axes=None, norm="backward"):
        jfn = getattr(jnp.fft, name)
        return _host_call(jfn, x, s=s,
                          axes=axes if axes is not None else default_axes,
                          norm=norm)

    def api(x, s=None, axes=default_axes, norm="backward", name=None):
        return _op(_t(x), s=s,
                   axes=tuple(axes) if axes is not None else None, norm=norm)
    api.__name__ = name
    api.__doc__ = f"reference python/paddle/fft.py {name}."
    return api


fft2 = _mknd("fft2", default_axes=(-2, -1))
ifft2 = _mknd("ifft2", default_axes=(-2, -1))
rfft2 = _mknd("rfft2", default_axes=(-2, -1))
irfft2 = _mknd("irfft2", default_axes=(-2, -1))
fftn = _mknd("fftn")
ifftn = _mknd("ifftn")
rfftn = _mknd("rfftn")
irfftn = _mknd("irfftn")


@defop("fftshift")
def _fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    """reference fft.py fftshift."""
    return _fftshift(_t(x), axes=axes)


@defop("ifftshift")
def _ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    """reference fft.py ifftshift."""
    return _ifftshift(_t(x), axes=axes)


def fftfreq(n, d=1.0, dtype=None, name=None):
    """reference fft.py fftfreq."""
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    """reference fft.py rfftfreq."""
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def _mk_hermitian(name):
    """hfft2/hfftn/ihfft2/ihfftn (reference fft.py fftn_c2r/fftn_r2c
    Hermitian n-d paths). scipy.fft provides the semantics; host-side
    eager like the reference CPU kernels (complex in/out is unsupported
    on the TPU systolic path anyway)."""
    import scipy.fft as sfft
    sfn = getattr(sfft, name)
    default_axes = (-2, -1) if name.endswith("2") else None

    def api(x, s=None, axes=default_axes, norm="backward", name=None):
        import numpy as np
        arr = np.asarray(_t(x)._value)
        out = sfn(arr, s=s, axes=axes, norm=norm)
        return Tensor(jnp.asarray(out))
    api.__name__ = name
    api.__doc__ = f"reference python/paddle/fft.py {name}."
    return api


hfft2 = _mk_hermitian("hfft2")
ihfft2 = _mk_hermitian("ihfft2")
hfftn = _mk_hermitian("hfftn")
ihfftn = _mk_hermitian("ihfftn")
__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
