"""paddle_tpu.onnx (reference: python/paddle/onnx/export.py — thin
delegation to paddle2onnx). TPU artifacts are StableHLO, which ONNX
tooling cannot consume directly; export raises with the supported path
unless paddle2onnx-compatible tooling is installed."""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference onnx/export.py export."""
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "ONNX export requires paddle2onnx, which is not installed in "
            "this TPU build. The supported deployment artifact is "
            "paddle.jit.save's StableHLO bundle (servable with "
            "paddle.inference.create_predictor); convert to ONNX offline "
            "from the StableHLO if needed.") from None
