"""paddle_tpu.onnx (reference: python/paddle/onnx/export.py — a thin
delegation to the external paddle2onnx converter).

TPU-native stance (SURVEY §7.4): the portable deployment artifact of
this framework is StableHLO, not ONNX — XLA consumes StableHLO
directly, and ONNX cannot express sharded/pallas programs. ``export``
therefore writes a **bridge artifact** riding ``paddle.jit.save``:

Bridge artifact format (v1), two files at ``path``:
  - ``<path>.pdmodel`` — pickled dict with keys ``state`` (numpy
    weights), ``stablehlo`` (jax.export portable bytes of forward),
    ``input_meta`` (shape/dtype/name per input), ``meta``.
  - ``<path>.bridge.json`` — plain-JSON manifest: format tag
    ``paddle_tpu-onnx-bridge/1``, input metadata, opset requested,
    pointer to the .pdmodel. Offline conversion to real ONNX is any
    stablehlo→onnx toolchain (e.g. onnx-mlir / paddle2onnx where
    available); when the ``paddle2onnx`` package is importable,
    ``export`` delegates to it instead.
"""

from __future__ import annotations

import json

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` for interchange (reference onnx/export.py
    export). With paddle2onnx installed, delegates to it; otherwise
    writes the documented StableHLO bridge artifact (see module
    docstring) and returns the manifest path."""
    try:
        import paddle2onnx  # noqa: F401
        have_p2o = True
    except ImportError:
        have_p2o = False
    if have_p2o:  # pragma: no cover — not installed in the TPU image
        import paddle2onnx as p2o
        # reference export.py:102 delegates via dygraph2onnx with the
        # '.onnx' suffix appended to the path prefix
        return p2o.dygraph2onnx(layer, path + ".onnx",
                                input_spec=input_spec,
                                opset_version=opset_version, **configs)
    if input_spec is None:
        raise ValueError(
            "onnx.export without paddle2onnx requires input_spec (the "
            "StableHLO bridge needs static input shapes to trace "
            "forward)")
    from .. import jit as _jit
    payload = _jit.save(layer, path, input_spec=input_spec)
    if payload.get("stablehlo") is None:
        raise RuntimeError(
            "onnx.export: forward could not be traced to StableHLO "
            "(see the jit.save warning above); nothing portable to "
            "bridge")
    manifest = {
        "format": "paddle_tpu-onnx-bridge/1",
        "model": path.rsplit("/", 1)[-1] + ".pdmodel",
        "opset_version_requested": int(opset_version),
        "inputs": payload.get("input_meta"),
        "note": ("StableHLO portable bytes + weights; convert offline "
                 "with a stablehlo->onnx toolchain, or load with "
                 "paddle.jit.load for serving"),
    }
    mpath = path + ".bridge.json"
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=2)
    return mpath
