"""Throughput benchmark timer (reference: python/paddle/profiler/timer.py
— Benchmark with reader/batch cost and ips, `benchmark()` singleton)."""

from __future__ import annotations

import time

__all__ = ["Benchmark", "benchmark"]


class _Stat:
    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.samples = 0

    def add(self, dt, samples=None):
        self.total += dt
        self.count += 1
        if samples:
            self.samples += samples

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0


class Benchmark:
    """reference timer.py Benchmark — step timing + ips.

    b = profiler.benchmark(); b.begin()
    for batch in loader: train(); b.step(len(batch))
    print(b.report())
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self._step_start = None
        self._begin_time = None
        self.batch_cost = _Stat()
        self.speed_unit = "samples/s"

    def begin(self):
        self._begin_time = time.perf_counter()
        self._step_start = self._begin_time

    def step(self, num_samples: int | None = None):
        now = time.perf_counter()
        if self._step_start is not None:
            self.batch_cost.add(now - self._step_start, num_samples)
        self._step_start = now

    def end(self):
        self._step_start = None

    def step_info(self, unit=None):
        c = self.batch_cost
        ips = (c.samples / c.total) if (c.total and c.samples) else 0.0
        return (f"batch_cost: {c.avg:.5f} s, ips: {ips:.2f} "
                f"{unit or self.speed_unit}")

    def report(self):
        c = self.batch_cost
        return {"batch_cost_avg": c.avg,
                "steps": c.count,
                "ips": (c.samples / c.total)
                if (c.total and c.samples) else 0.0}


_BENCH = Benchmark()


def benchmark() -> Benchmark:
    """reference timer.py benchmark() — global singleton."""
    return _BENCH
