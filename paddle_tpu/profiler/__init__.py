"""paddle_tpu.profiler — host spans + device tracing (reference:
python/paddle/profiler/profiler.py — Profiler:346, ProfilerState:79,
export_chrome_tracing:215; C++ host tracer platform/profiler/profiler.h:47
with RecordEvent spans and a CUPTI device tracer merged into one timeline).

TPU-native split:
- host spans: ``RecordEvent`` context manager into a process-global ring
  buffer; ops auto-annotated at dispatch via core.dispatch.OP_OBSERVERS
  (the reference annotates kernels at dispatch the same way);
- device timeline: ``jax.profiler`` xplane trace (TensorBoard-viewable),
  started/stopped with the profiler when ``trace_dir`` is set — XLA's
  profiler is the CUPTI analogue;
- exports: chrome-trace JSON of the host spans + a stats summary table
  (reference profiler_statistic.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from enum import Enum

from .timer import Benchmark, benchmark  # noqa: F401

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "Benchmark", "benchmark"]


class ProfilerState(Enum):
    """reference profiler.py:79."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


@dataclass
class _Span:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    kind: str = "user"
    worker: str | None = None   # fleet worker lane (ISSUE 5 export)


class _SpanBuffer:
    """Process-global span store (reference host_event_recorder.h ring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spans: list[_Span] = []
        self.enabled = False

    def add(self, span):
        with self._lock:
            self.spans.append(span)

    def drain(self):
        with self._lock:
            out = self.spans
            self.spans = []
            return out


_BUFFER = _SpanBuffer()


class RecordEvent:
    """reference python/paddle/profiler/utils.py RecordEvent — host span;
    usable as context manager or begin()/end() pair."""

    def __init__(self, name: str, event_type: str = "user",
                 worker: str | None = None):
        self.name = name
        self.event_type = event_type
        self.worker = worker        # fleet worker attribution (ISSUE 5)
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is None or not _BUFFER.enabled:
            self._start = None
            return
        _BUFFER.add(_Span(self.name, self._start, time.perf_counter_ns(),
                          threading.get_ident(), self.event_type,
                          self.worker))
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    """reference profiler.py make_scheduler — step → ProfilerState."""

    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str | None = None):
    """reference profiler.py:215 — on_trace_ready factory writing
    chrome://tracing JSON."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        handler._serial = getattr(handler, "_serial", 0) + 1
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time())}_"
            f"{handler._serial}.paddle_trace.json")
        prof._export_chrome(path)
        return path

    return handler


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


class Profiler:
    """reference profiler.py Profiler:346.

    with profiler.Profiler(on_trace_ready=export_chrome_tracing('./log'))
    as p:
        for batch in loader:
            train_step(batch)
            p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir: str | None = None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False):
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            # the window's last step must be RECORD_AND_RETURN so
            # on_trace_ready fires when the window closes (reference maps
            # the tuple form the same way)
            self._scheduler = lambda step: (
                ProfilerState.RECORD_AND_RETURN if step == end - 1
                else ProfilerState.RECORD if start <= step < end
                else ProfilerState.CLOSED)
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._trace_dir = trace_dir
        self._timer_only = timer_only
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._spans: list[_Span] = []
        self._op_counts: dict[str, int] = {}
        self._observer = None
        self._device_tracing = False
        self.benchmark = Benchmark()

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.state = self._scheduler(self.step_num)
        if self.state in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN):
            self._enable()
        self.benchmark.begin()
        return self

    def stop(self):
        was_recording = _BUFFER.enabled
        if was_recording:
            self._collect()
        self._disable()
        self.benchmark.end()
        # export only when a live recording window is being closed here —
        # RECORD_AND_RETURN windows already exported in step(), and a
        # fully-CLOSED run has nothing to write
        if was_recording and self._on_trace_ready is not None \
                and not self._timer_only:
            self._on_trace_ready(self)

    def step(self, num_samples: int | None = None):
        """Advance the scheduler one iteration (reference Profiler.step)."""
        self.benchmark.step(num_samples)
        prev = self.state
        self.step_num += 1
        self.state = self._scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev in recording:
            self._collect()
        if prev == ProfilerState.RECORD_AND_RETURN \
                and self._on_trace_ready is not None:
            self._on_trace_ready(self)
            # each window exports its own events only
            self._spans = []
            self._op_counts = {}
        if self.state in recording and not _BUFFER.enabled:
            self._enable()
        elif self.state not in recording and _BUFFER.enabled:
            self._disable()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- internals ----------------------------------------------------------
    def _enable(self):
        _BUFFER.enabled = True
        if self._observer is None:
            from ..core.dispatch import OP_OBSERVERS

            def obs(name):
                now = time.perf_counter_ns()
                _BUFFER.add(_Span(name, now, now, threading.get_ident(),
                                  "op"))
            self._observer = obs
            OP_OBSERVERS.append(obs)
        if self._trace_dir and not self._device_tracing:
            import jax
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._device_tracing = True
            except Exception:  # noqa: BLE001 — device tracing best-effort
                self._device_tracing = False

    def _disable(self):
        _BUFFER.enabled = False
        if self._observer is not None:
            from ..core.dispatch import OP_OBSERVERS
            if self._observer in OP_OBSERVERS:
                OP_OBSERVERS.remove(self._observer)
            self._observer = None
        if self._device_tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                self._device_tracing = False

    def _collect(self):
        spans = _BUFFER.drain()
        self._spans.extend(spans)
        for s in spans:
            if s.kind == "op":
                self._op_counts[s.name] = self._op_counts.get(s.name, 0) + 1

    # -- outputs ------------------------------------------------------------
    def _export_chrome(self, path: str):
        events = []
        for s in self._spans:
            if s.kind == "op":
                events.append({"name": s.name, "ph": "i",
                               "ts": s.start_ns / 1e3, "pid": os.getpid(),
                               "tid": s.tid, "s": "t", "cat": "op"})
            else:
                # cat carries the span kind ("user", "engine", ...), so
                # the serving lifecycle spans the DecodeEngine emits
                # render as their own category in one unified timeline
                # next to op-dispatch instants (ISSUE 3)
                events.append({"name": s.name, "ph": "X",
                               "ts": s.start_ns / 1e3,
                               "dur": (s.end_ns - s.start_ns) / 1e3,
                               "pid": os.getpid(), "tid": s.tid,
                               "cat": s.kind})
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path

    def export_chrome_tracing(self, path: str):
        return self._export_chrome(path)

    export = export_chrome_tracing

    def summary(self, sorted_by="total", op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated span statistics (reference profiler_statistic.py)."""
        agg: dict[str, list[float]] = {}
        for s in self._spans:
            if s.kind == "op":
                continue
            dur = (s.end_ns - s.start_ns) / 1e6
            rec = agg.setdefault(s.name, [0, 0.0, float("inf"), 0.0])
            rec[0] += 1
            rec[1] += dur
            rec[2] = min(rec[2], dur)
            rec[3] = max(rec[3], dur)
        lines = [f"{'Name':<32}{'Calls':>8}{'Total(ms)':>12}"
                 f"{'Avg(ms)':>12}{'Min(ms)':>12}{'Max(ms)':>12}",
                 "-" * 88]
        for name, (cnt, tot, mn, mx) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<32}{cnt:>8}{tot:>12.3f}"
                         f"{tot / cnt:>12.3f}{mn:>12.3f}{mx:>12.3f}")
        if self._op_counts:
            lines.append("-" * 88)
            lines.append("Op dispatch counts:")
            for name, cnt in sorted(self._op_counts.items(),
                                    key=lambda kv: -kv[1])[:40]:
                lines.append(f"  {name:<38}{cnt:>8}")
        table = "\n".join(lines)
        print(table)
        return {"events": {k: {"calls": v[0], "total_ms": v[1],
                               "min_ms": v[2], "max_ms": v[3]}
                           for k, v in agg.items()},
                "op_counts": dict(self._op_counts)}


class SortedKeys(Enum):
    """Sort keys for summary tables (reference: profiler/profiler_statistic.py
    SortedKeys)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(Enum):
    """Summary table views (reference: profiler/profiler.py SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name: str | None = None):
    """Protobuf-dump exporter (reference: profiler.py export_protobuf).
    The TPU build's interchange format is the chrome trace; this emits the
    same span payload serialized with pickle (protobuf schema owned by the
    reference's C++ tracer doesn't exist here) under .pb naming for
    tooling parity."""
    import os
    import pickle
    import socket
    import time

    def handle(prof):
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"host_{socket.gethostname()}"
        path = os.path.join(dir_name,
                            f"{worker}_{time.strftime('%Y%m%d%H%M%S')}.pb")
        with open(path, "wb") as f:
            pickle.dump([s.__dict__ for s in prof._spans], f)
        return path

    return handle


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]


# ---------------------------------------------------------------------------
# xplane parsing: device-time attribution without TensorBoard
# ---------------------------------------------------------------------------
def _pb_varint(buf, i):
    v = s = 0
    while True:
        b = buf[i]
        v |= (b & 0x7F) << s
        i += 1
        if not b & 0x80:
            return v, i
        s += 7


def _pb_fields(buf):
    """Minimal protobuf wire-format walker: yields (field_num, wire_type,
    value) — enough to read the tsl xplane schema without a TF/TSL
    dependency (the reference links the full TF profiler; here the trace
    IS jax's xplane and only the aggregation is ours)."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _pb_varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _pb_varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _pb_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"wire type {wt}")
        yield fnum, wt, v


def _xplane_planes(space_bytes):
    """XSpace.planes=1 -> (name, lines, event_metadata) per plane.
    Schema: tsl/profiler/protobuf/xplane.proto — XPlane{name=2, lines=3,
    event_metadata=4}, XLine{id=1, name=2, timestamp_ns=3, events=4},
    XEvent{metadata_id=1, duration_ps=3, num_occurrences=5},
    XEventMetadata{id=1, name=2}."""
    for fnum, _, plane in _pb_fields(space_bytes):
        if fnum != 1:
            continue
        name, lines, emeta = "", [], {}
        for pf, _, pv in _pb_fields(plane):
            if pf == 2:
                name = pv.decode("utf-8", "replace")
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:
                mid, mname = 0, ""
                for ef, _, ev in _pb_fields(pv):
                    if ef == 1:
                        mid = ev
                    elif ef == 2:
                        for mf, _, mv in _pb_fields(ev):
                            if mf == 1:
                                mid = mv
                            elif mf == 2:
                                mname = mv.decode("utf-8", "replace")
                emeta[mid] = mname
        yield name, lines, emeta


def xplane_op_breakdown(trace_dir, top=20):
    """Aggregate per-op device time from a jax.profiler xplane trace
    (Profiler(trace_dir=...) or jax.profiler.start_trace). Returns
    {"device": plane_name, "total_ms": T, "ops": [(name, ms, share), ...],
    "groups": {category: (ms, share)}} for the busiest device plane's
    'XLA Ops' line — the attribution the reference reads out of its CUPTI
    timeline (SURVEY §5 tracing)."""
    import glob
    import os
    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    space = open(max(paths, key=os.path.getmtime), "rb").read()
    best = None
    for pname, lines, emeta in _xplane_planes(space):
        if "TPU" not in pname and "GPU" not in pname \
                and "device" not in pname.lower():
            continue
        per_op: dict[str, float] = {}
        for line in lines:
            lname, events = "", []
            for lf, wt, lv in _pb_fields(line):
                if lf == 2 and wt == 2:
                    lname = lv.decode("utf-8", "replace")
                elif lf == 4 and wt == 2:
                    events.append(lv)
            if "Ops" not in lname:
                continue
            for ev in events:
                mid = dur = 0
                occ = 1
                for ef, _, evv in _pb_fields(ev):
                    if ef == 1:
                        mid = evv
                    elif ef == 3:
                        dur = evv
                    elif ef == 5:
                        occ = evv
                nm = emeta.get(mid, str(mid))
                per_op[nm] = per_op.get(nm, 0.0) + dur * max(occ, 1)
        total = sum(per_op.values())
        if best is None or total > best[1]:
            best = (pname, total, per_op)
    if best is None or not best[2]:
        raise ValueError("no device 'XLA Ops' line found in the trace")
    pname, total_ps, per_op = best

    def short(op_name):
        # "%fusion.123 = bf16[...] ..." -> "fusion.123"
        n = op_name.split(" = ")[0].strip()
        return n[1:] if n.startswith("%") else n

    def category(op_name):
        n = short(op_name).lower()
        if any(t in n for t in ("dot", "conv", "einsum")):
            return "matmul"
        if any(t in n for t in ("all-reduce", "all-gather", "collective",
                                "reduce-scatter", "all-to-all",
                                "permute")):
            return "collective"
        if any(t in n for t in ("flash", "attention")):
            return "attention_kernel"
        if any(t in n for t in ("copy", "transpose", "reshape", "bitcast",
                                "slice", "concatenate", "pad")):
            return "data_movement"
        if "fusion" in n:
            return "fusion(elementwise+)"
        return "other"

    groups: dict[str, float] = {}
    for nm, ps in per_op.items():
        groups[category(nm)] = groups.get(category(nm), 0.0) + ps
    ops_sorted = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    return {
        "device": pname,
        "total_ms": total_ps / 1e9,
        "ops": [(short(nm), ps / 1e9, ps / total_ps)
                for nm, ps in ops_sorted],
        "groups": {g: (ps / 1e9, ps / total_ps)
                   for g, ps in sorted(groups.items(),
                                       key=lambda kv: -kv[1])},
    }


__all__ += ["xplane_op_breakdown"]
