"""General utilities (reference: python/paddle/utils/ — deprecated
decorator, install_check.run_check, lazy_import try_import,
require_version)."""

from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["deprecated", "require_version", "run_check", "try_import"]


def deprecated(update_to="", since="", reason="", level=0):
    """Mark an API deprecated; warns on call (reference:
    utils/deprecated.py)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def require_version(min_version, max_version=None):
    """Check the installed framework version (reference:
    utils/__init__.py require_version)."""
    from .. import __version__

    def as_tuple(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = as_tuple(__version__)
    if as_tuple(min_version) > cur:
        raise Exception(
            f"version {__version__} < required minimum {min_version}")
    if max_version is not None and as_tuple(max_version) < cur:
        raise Exception(
            f"version {__version__} > allowed maximum {max_version}")
    return True


def run_check(verbose=True):
    """Smoke-check the install: run a tiny matmul on the default device
    (reference: utils/install_check.py run_check)."""
    import numpy as np
    import paddle_tpu as p
    a = p.to_tensor(np.ones((2, 2), dtype="float32"))
    out = (a @ a).numpy()
    assert np.allclose(out, 2 * np.ones((2, 2)))
    if verbose:
        import jax
        print(f"paddle_tpu is installed successfully! "
              f"backend={jax.default_backend()}, "
              f"devices={len(jax.devices())}")
    return True


def try_import(module_name, err_msg=None):
    """Import a module or raise a helpful error (reference:
    utils/lazy_import.py try_import)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Failed to import {module_name}: {e}. "
            f"Install it to use this feature.") from e
