"""Out-of-tree custom C++ ops (reference: paddle/phi/api/ext/
op_meta_info.h:850 PD_BUILD_OP + python/paddle/utils/cpp_extension/
cpp_extension.py — setup:79 / load:797 JIT build, BuildExtension:357).

TPU-native split of the reference's custom-op story:
- custom DEVICE kernels → write Pallas (jax.experimental.pallas); they
  are jit-compiled for the MXU like the in-tree flash attention.
- custom HOST ops (pre/post-processing, tokenizers, CPU-only math) →
  this module: ``load()`` JIT-compiles C++ with the system toolchain into
  a shared library and registers each exported function as a framework op
  executed through ``jax.pure_callback`` (works eagerly and inside jit;
  the host transfer is explicit, as it would be on any accelerator).

C ABI (simplified ``PD_BUILD_OP``): each op is
``extern "C" void name(const float* in0[, const float* in1, ...],
float* out, int64_t n)`` over contiguous float32 buffers; the output has
the shape of input 0. An optional ``name_grad`` symbol with the same
arity + incoming-cotangent buffer provides the backward."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np

__all__ = ["load", "CppExtension", "setup", "get_build_directory"]


def get_build_directory():
    root = os.environ.get("PADDLE_EXTENSION_DIR",
                          os.path.join(os.path.expanduser("~"),
                                       ".cache", "paddle_tpu_extensions"))
    os.makedirs(root, exist_ok=True)
    return root


def _compile(name, sources, extra_cflags, build_directory, verbose):
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    digest = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            digest.update(f.read())
    digest.update(" ".join(extra_cflags or []).encode())
    so_path = os.path.join(build_dir, f"{name}_{digest.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *(extra_cflags or []), *sources, "-o", so_path]
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"g++ failed (exit {proc.returncode}) for {name}:\n"
                f"{proc.stderr}")
        if verbose and proc.stderr:
            print(proc.stderr)
    return so_path


class _Extension:
    """Module-like handle over the compiled library: each declared op is a
    framework-op callable (Tensor in/out, jit-safe)."""

    def __init__(self, lib_path, functions):
        self._lib_path = lib_path
        self._lib = ctypes.CDLL(lib_path)
        for fname, n_inputs in functions.items():
            setattr(self, fname, self._make_op(fname, n_inputs))

    def _sym(self, fname, n_bufs):
        sym = getattr(self._lib, fname)
        sym.restype = None
        sym.argtypes = [ctypes.POINTER(ctypes.c_float)] * n_bufs \
            + [ctypes.c_int64]
        return sym

    def _host_call(self, sym):
        def host_fn(*arrays):
            ins = [np.ascontiguousarray(a, np.float32) for a in arrays]
            out = np.empty_like(ins[0])
            ptrs = [a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                    for a in ins]
            sym(*ptrs, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ctypes.c_int64(ins[0].size))
            return out
        return host_fn

    def _make_op(self, fname, n_inputs):
        import jax
        import jax.numpy as jnp

        from ..core.dispatch import apply_op
        from ..core.tensor import Tensor

        fwd_host = self._host_call(self._sym(fname, n_inputs + 1))
        try:
            bwd_host = self._host_call(self._sym(fname + "_grad",
                                                 n_inputs + 2))
        except AttributeError:
            bwd_host = None

        def fwd_raw(*arrs):
            if not any(isinstance(a, jax.core.Tracer) for a in arrs):
                # eager: call the C++ symbol directly on host (some PJRT
                # plugins — e.g. the axon tunnel — don't support
                # pure_callback at all, and eager needs no callback)
                return jnp.asarray(fwd_host(*[np.asarray(a)
                                              for a in arrs]))
            spec = jax.ShapeDtypeStruct(arrs[0].shape, jnp.float32)
            return jax.pure_callback(fwd_host, spec, *arrs,
                                     vmap_method="sequential")

        if bwd_host is None:
            def op(*tensors):
                ts = tuple(t if isinstance(t, Tensor)
                           else Tensor(jnp.asarray(t)) for t in tensors)
                return apply_op(f"custom_{fname}", fwd_raw, ts, {},
                                differentiable=False)
            op.__name__ = fname
            return op

        import functools

        @functools.partial(jax.custom_vjp)
        def fwd_diff(*arrs):
            return fwd_raw(*arrs)

        def _vjp_fwd(*arrs):
            return fwd_raw(*arrs), arrs

        def _vjp_bwd(res, g):
            # ABI: name_grad(in0[, in1...], cot, out, n) -> d/d_in0 only
            # (multi-input customs return the same-shaped grad for input 0
            # and zeros for the rest, like reference single-grad customs)
            if not any(isinstance(a, jax.core.Tracer) for a in (*res, g)):
                din0 = jnp.asarray(bwd_host(*[np.asarray(a) for a in res],
                                            np.asarray(g)))
            else:
                spec = jax.ShapeDtypeStruct(res[0].shape, jnp.float32)
                din0 = jax.pure_callback(bwd_host, spec, *res, g,
                                         vmap_method="sequential")
            return (din0,) + tuple(jnp.zeros_like(a) for a in res[1:])

        fwd_diff.defvjp(_vjp_fwd, _vjp_bwd)

        def op(*tensors):
            ts = tuple(t if isinstance(t, Tensor)
                       else Tensor(jnp.asarray(t)) for t in tensors)
            return apply_op(f"custom_{fname}", fwd_diff, ts, {})
        op.__name__ = fname
        return op


def load(name, sources, functions=None, extra_cflags=None,
         extra_cuda_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False):
    """reference cpp_extension.load:797 — JIT-compile and import.

    ``functions`` maps exported symbol → number of tensor inputs; if
    omitted, every ``extern "C"`` symbol must be declared via a
    ``// PD_OP: name n_inputs`` comment line in the source."""
    sources = [sources] if isinstance(sources, str) else list(sources)
    if functions is None:
        functions = {}
        for src in sources:
            with open(src) as f:
                for line in f:
                    if line.strip().startswith("// PD_OP:"):
                        parts = line.strip().split()
                        functions[parts[2]] = int(parts[3])
        if not functions:
            raise ValueError(
                "declare ops via functions={name: n_inputs} or "
                "'// PD_OP: name n_inputs' comments in the source")
    if extra_include_paths:
        extra_cflags = list(extra_cflags or []) + [
            f"-I{p}" for p in extra_include_paths]
    so_path = _compile(name, sources, extra_cflags, build_directory,
                       verbose)
    return _Extension(so_path, functions)


class CppExtension:
    """reference cpp_extension.CppExtension — declarative form consumed by
    :func:`setup`."""

    def __init__(self, sources, functions=None, **kwargs):
        self.sources = [sources] if isinstance(sources, str) else sources
        self.functions = functions
        self.kwargs = kwargs


def setup(name, ext_modules, **kwargs):
    """reference cpp_extension.setup:79 — eager build (no wheel machinery;
    returns the loaded extension)."""
    ext = ext_modules if isinstance(ext_modules, CppExtension) \
        else ext_modules[0]
    return load(name, ext.sources, ext.functions,
                extra_cflags=ext.kwargs.get("extra_compile_args"))
