"""paddle_tpu.utils (reference: python/paddle/utils/ — cpp_extension,
unique_name, deprecated helpers)."""

from . import cpp_extension  # noqa: F401
from .helpers import deprecated, require_version, run_check, try_import  # noqa: F401

__all__ = ["cpp_extension", "deprecated", "require_version", "run_check",
           "try_import"]
