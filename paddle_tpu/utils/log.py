"""Structured logging / observability layer (SURVEY §5 item 57;
reference: base/log_helper.py get_logger + glog VLOG levels + the
launch/elastic loggers writing per-rank files).

Two surfaces:
- :func:`get_logger` — classic python logger with the reference's
  format, level from ``GLOG_v`` (0=warning, 1=info, 2+=debug).
- :class:`EventLog` — STRUCTURED JSON-lines events (step metrics, comm
  timeouts, checkpoint saves/resumes, elastic transitions). One line per
  event: {"ts": ..., "event": ..., "rank": ..., **fields}. Sinks:
  stderr, a file (PADDLE_LOG_DIR/events.rank{N}.jsonl), or any callable;
  in-memory ring buffer for tests/tools. Subsystems emit through
  :func:`log_event` so operators can grep ONE stream for what the
  runtime did."""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from collections import deque

__all__ = ["get_logger", "EventLog", "log_event", "default_event_log",
           "kv_line", "log_kv"]

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

_LEVEL_NAMES = {"debug": logging.DEBUG, "info": logging.INFO,
                "warning": logging.WARNING, "warn": logging.WARNING,
                "error": logging.ERROR, "critical": logging.CRITICAL}


def _glog_level() -> int:
    """Level resolution: ``PT_LOG_LEVEL`` (name or numeric, the serving
    stack's knob) wins over the reference's ``GLOG_v`` verbosity."""
    pt = os.environ.get("PT_LOG_LEVEL", "").strip().lower()
    if pt:
        if pt in _LEVEL_NAMES:
            return _LEVEL_NAMES[pt]
        try:
            return int(pt)
        except ValueError:
            pass
    try:
        v = int(os.environ.get("GLOG_v", "0"))
    except ValueError:
        v = 0
    return {0: logging.WARNING, 1: logging.INFO}.get(v, logging.DEBUG)


def get_logger(name, level=None, fmt=_FMT):
    """reference base/log_helper.py:20 — a configured logger that does
    not propagate into the root logger."""
    logger = logging.getLogger(name)
    logger.setLevel(level if level is not None else _glog_level())
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
    logger.propagate = False
    return logger


def kv_line(event: str, **fields) -> str:
    """``event key=value key=value`` — the structured single-line form
    engine/server log lines use instead of bare prints (ISSUE 3
    satellite: greppable fields like request id / row / pages)."""
    if not fields:
        return event
    return event + " " + " ".join(
        f"{k}={v}" for k, v in fields.items())


def log_kv(logger, event: str, *, level=logging.INFO, **fields) -> str:
    """Emit a ``key=value`` structured line through a classic logger
    (level-gated by ``PT_LOG_LEVEL``/``GLOG_v``). Returns the line."""
    line = kv_line(event, **fields)
    logger.log(level, line)
    return line


class EventLog:
    """JSON-lines structured event stream with an in-memory ring."""

    def __init__(self, path=None, stream=None, ring_size=1024):
        self._path = path
        self._stream = stream
        self._file = None
        self.ring = deque(maxlen=ring_size)
        self._sinks = []

    def add_sink(self, fn):
        """fn(record_dict) — e.g. a metrics exporter."""
        self._sinks.append(fn)
        return fn

    def _rank(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    def emit(self, event: str, **fields):
        rec = {"ts": round(time.time(), 3), "event": event,
               "rank": self._rank(), **fields}
        self.ring.append(rec)
        line = json.dumps(rec, default=str)
        if self._stream is not None:
            print(line, file=self._stream, flush=True)
        if self._path:
            if self._file is None:
                os.makedirs(os.path.dirname(self._path) or ".",
                            exist_ok=True)
                self._file = open(self._path, "a")
            self._file.write(line + "\n")
            self._file.flush()
        for s in self._sinks:
            try:
                s(rec)
            except Exception:  # noqa: BLE001 — sinks must not break training
                pass
        return rec

    def events(self, event=None):
        return [r for r in self.ring if event is None or r["event"] == event]


def _default_path():
    d = os.environ.get("PADDLE_LOG_DIR")
    if not d:
        return None
    return os.path.join(
        d, f"events.rank{os.environ.get('PADDLE_TRAINER_ID', '0')}.jsonl")


default_event_log = EventLog(path=_default_path())


def log_event(event: str, **fields):
    """Emit to the process-default structured event log."""
    return default_event_log.emit(event, **fields)
