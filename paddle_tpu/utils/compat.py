"""jax version compatibility shims.

The codebase targets the jax 0.5+ surface; the pinned toolchain may
carry an older jax where some of those names live under
``jax.experimental`` with an earlier API. Every shim resolves the NEW
spelling first so nothing changes on a current jax.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              axis_names=None, check_vma=None):
    """jax.shard_map with the new keyword surface, adapted to the old
    ``jax.experimental.shard_map.shard_map`` when needed:
    ``axis_names`` (manual axes) becomes its complement ``auto``, and
    ``check_vma`` maps to ``check_rep``."""
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    # the old replication checker predates vma tracking: it has no rules
    # for primitives like checkpoint_name's `name`, and (unlike new
    # jax's check_vma=False) turning it off does NOT demote the region
    # to full-manual. ``axis_names`` is dropped on purpose: the old
    # ``auto=`` partial-manual lowers axis_index to a PartitionId op the
    # SPMD partitioner rejects (UNIMPLEMENTED, and an outright abort on
    # a compile retry). Full manual with the same specs is value-
    # equivalent — axes the specs don't mention are replicated instead
    # of left to GSPMD, and the body only runs collectives over the
    # manual axes either way.
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def get_abstract_mesh():
    """jax.sharding.get_abstract_mesh, or None before jax 0.5 (callers
    treat None as "not inside a manual region")."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    return fn() if fn is not None else None
