"""paddle_tpu.quantization — QAT / PTQ (reference:
python/paddle/quantization/ — config.py QuantConfig:60, qat.py QAT,
ptq.py PTQ, observers/ (AbsmaxObserver), quanters/
(FakeQuanterWithAbsMaxObserver), wrapper.py quanted layer wrapping).

TPU-native: fake-quantization is a pure jnp round-trip with a
straight-through-estimator custom vjp — one fused XLA kernel per site —
and bf16/int8 simulation stays on the MXU-friendly dense path."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply_op
from ..core.tensor import Tensor

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver", "quant", "dequant",
           "QuantedLinear"]


# ---------------------------------------------------------------------------
# fake-quant core: STE (reference quanters/abs_max.py forward/backward)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax


def _fq_fwd(x, scale, bits):
    return _fake_quant(x, scale, bits), (x, scale)


def _fq_bwd(bits, res, g):
    x, scale = res
    # straight-through inside the clip range (reference fake_quant bwd)
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    inside = jnp.abs(x / s * qmax) <= qmax
    return (jnp.where(inside, g, 0.0), jnp.zeros_like(scale))


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant(x, scale, bits=8):
    """Simulated quantize-dequantize with STE gradients."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    sc = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale))
    return apply_op("fake_quant",
                    lambda xv, sv: _fake_quant(xv, sv, bits), (t, sc), {})


def dequant(x, scale, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return Tensor(t._value * jnp.asarray(scale) / qmax)


# ---------------------------------------------------------------------------
# observers / quanters (reference observers/abs_max.py, quanters/abs_max.py)
# ---------------------------------------------------------------------------
class AbsmaxObserver:
    """reference observers/abs_max.py AbsmaxObserver — running abs-max."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._max = 0.0

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if isinstance(v, jax.core.Tracer):
            raise RuntimeError(
                "AbsmaxObserver.observe needs concrete values — run "
                "calibration eagerly, then jit the converted model")
        self._max = max(self._max, float(jnp.max(jnp.abs(v))))
        return self._max

    def scale(self):
        return self._max

    def _instance(self, layer):
        return AbsmaxObserver(self.quant_bits)


class FakeQuanterWithAbsMaxObserver:
    """reference quanters/abs_max.py — moving-average abs-max fake
    quantizer applied during QAT."""

    def __init__(self, moving_rate=0.9, bit_length=8):
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = None

    def _instance(self, layer):
        return FakeQuanterWithAbsMaxObserver(self.moving_rate,
                                             self.bit_length)

    def __call__(self, x):
        t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        if isinstance(t._value, jax.core.Tracer):
            # under jit/to_static tracing: compute the scale in-graph
            # (dynamic abs-max) — float() on a tracer would crash, and the
            # moving average is an eager-mode statistic
            bits = self.bit_length
            return apply_op(
                "fake_quant_dyn",
                lambda xv: _fake_quant(
                    xv, jnp.max(jnp.abs(xv)), bits), (t,), {})
        cur = float(jnp.max(jnp.abs(t._value)))
        if self._scale is None:
            self._scale = cur
        else:
            r = self.moving_rate
            self._scale = r * self._scale + (1 - r) * cur
        return quant(t, self._scale or 1e-8, self.bit_length)


# ---------------------------------------------------------------------------
# config (reference config.py QuantConfig:60)
# ---------------------------------------------------------------------------
class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        # per-type quanter config (reference SingleLayerConfig map);
        # only nn.Linear has a quanted wrapper so far
        self._type_configs = {nn.Linear: (activation, weight)}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            if t is not nn.Linear:
                raise NotImplementedError(
                    f"quantization wrapper for {t.__name__} not "
                    f"implemented (Linear only)")
            self._type_configs[t] = (activation or self.activation,
                                     weight or self.weight)


class QuantedLinear(nn.Layer):
    """reference wrapper.py quanted layer: fake-quant weight (+activation)
    around the float matmul."""

    def __init__(self, layer: nn.Linear, config: QuantConfig):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = layer.bias
        act, wt = config._type_configs.get(
            type(layer), (config.activation, config.weight))
        self.activation_quanter = act._instance(layer) if act else None
        self.weight_quanter = wt._instance(layer) if wt else None

    def forward(self, x):
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        from ..nn import functional as F
        return F.linear(x, w, self.bias)


def _wrap_layers(model, config):
    for name, child in list(model._sub_layers.items()):
        if type(child) in config._type_configs:
            model._sub_layers[name] = QuantedLinear(child, config)
        else:
            _wrap_layers(child, config)
    return model


class QAT:
    """reference qat.py QAT — quantize() wraps target layers with fake
    quanters; train as usual; convert() re-materializes float weights from
    their quantized form."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return _wrap_layers(model, self.config)

    def convert(self, model, inplace=False):
        """Bake fake-quant into the weights (deploy-form float sim)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear) \
                    and layer.weight_quanter is not None:
                q = layer.weight_quanter(layer.weight)
                layer.weight._in_place_update(q._value)
                layer.weight_quanter = None
        return model


class PTQ:
    """reference ptq.py PTQ — observe activations on calibration data,
    then convert with fixed scales."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        model = _wrap_layers(model, self.config)
        # PTQ: weight scales fixed immediately; activation quanters observe
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear):
                if layer.weight_quanter is not None:
                    layer.weight_quanter(layer.weight)  # set scale now
        return model

    def convert(self, model, inplace=False):
        return QAT(self.config).convert(model, inplace)
