"""paddle_tpu.quantization — QAT / PTQ (reference:
python/paddle/quantization/ — config.py QuantConfig:60, qat.py QAT,
ptq.py PTQ, observers/ (AbsmaxObserver), quanters/
(FakeQuanterWithAbsMaxObserver), wrapper.py quanted layer wrapping).

TPU-native: fake-quantization is a pure jnp round-trip with a
straight-through-estimator custom vjp — one fused XLA kernel per site —
and bf16/int8 simulation stays on the MXU-friendly dense path."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply_op
from ..core.tensor import Tensor

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver", "quant", "dequant",
           "QuantedLinear", "EMAObserver", "PercentileObserver"]


# ---------------------------------------------------------------------------
# fake-quant core: STE (reference quanters/abs_max.py forward/backward)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax


def _fq_fwd(x, scale, bits):
    return _fake_quant(x, scale, bits), (x, scale)


def _fq_bwd(bits, res, g):
    x, scale = res
    # straight-through inside the clip range (reference fake_quant bwd)
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    inside = jnp.abs(x / s * qmax) <= qmax
    return (jnp.where(inside, g, 0.0), jnp.zeros_like(scale))


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant(x, scale, bits=8):
    """Simulated quantize-dequantize with STE gradients."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    sc = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale))
    return apply_op("fake_quant",
                    lambda xv, sv: _fake_quant(xv, sv, bits), (t, sc), {})


def dequant(x, scale, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return Tensor(t._value * jnp.asarray(scale) / qmax)


# ---------------------------------------------------------------------------
# observers / quanters (reference observers/abs_max.py, quanters/abs_max.py)
# ---------------------------------------------------------------------------
class AbsmaxObserver:
    """reference observers/abs_max.py AbsmaxObserver — running abs-max."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._max = 0.0

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if isinstance(v, jax.core.Tracer):
            raise RuntimeError(
                "AbsmaxObserver.observe needs concrete values — run "
                "calibration eagerly, then jit the converted model")
        self._max = max(self._max, float(jnp.max(jnp.abs(v))))
        return self._max

    def scale(self):
        return self._max

    def _instance(self, layer):
        return AbsmaxObserver(self.quant_bits)


class FakeQuanterWithAbsMaxObserver:
    """reference quanters/abs_max.py — moving-average abs-max fake
    quantizer applied during QAT."""

    def __init__(self, moving_rate=0.9, bit_length=8):
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = None

    def _instance(self, layer):
        return FakeQuanterWithAbsMaxObserver(self.moving_rate,
                                             self.bit_length)

    def __call__(self, x):
        t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        if isinstance(t._value, jax.core.Tracer):
            # under jit/to_static tracing: compute the scale in-graph
            # (dynamic abs-max) — float() on a tracer would crash, and the
            # moving average is an eager-mode statistic
            bits = self.bit_length
            return apply_op(
                "fake_quant_dyn",
                lambda xv: _fake_quant(
                    xv, jnp.max(jnp.abs(xv)), bits), (t,), {})
        cur = float(jnp.max(jnp.abs(t._value)))
        if self._scale is None:
            self._scale = cur
        else:
            r = self.moving_rate
            self._scale = r * self._scale + (1 - r) * cur
        return quant(t, self._scale or 1e-8, self.bit_length)


# ---------------------------------------------------------------------------
# config (reference config.py QuantConfig:60)
# ---------------------------------------------------------------------------
class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        # per-type quanter config (reference SingleLayerConfig map);
        # only nn.Linear has a quanted wrapper so far
        self._type_configs = {nn.Linear: (activation, weight)}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            if t is not nn.Linear:
                raise NotImplementedError(
                    f"quantization wrapper for {t.__name__} not "
                    f"implemented (Linear only)")
            self._type_configs[t] = (activation or self.activation,
                                     weight or self.weight)


class QuantedLinear(nn.Layer):
    """reference wrapper.py quanted layer: fake-quant weight (+activation)
    around the float matmul."""

    def __init__(self, layer: nn.Linear, config: QuantConfig):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = layer.bias
        act, wt = config._type_configs.get(
            type(layer), (config.activation, config.weight))
        self.activation_quanter = act._instance(layer) if act else None
        self.weight_quanter = wt._instance(layer) if wt else None

    def forward(self, x):
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        from ..nn import functional as F
        return F.linear(x, w, self.bias)


def _wrap_layers(model, config):
    for name, child in list(model._sub_layers.items()):
        if type(child) in config._type_configs:
            model._sub_layers[name] = QuantedLinear(child, config)
        else:
            _wrap_layers(child, config)
    return model


class QAT:
    """reference qat.py QAT — quantize() wraps target layers with fake
    quanters; train as usual; convert() re-materializes float weights from
    their quantized form."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return _wrap_layers(model, self.config)

    def convert(self, model, inplace=False):
        """Bake fake-quant into the weights (deploy-form float sim)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear) \
                    and layer.weight_quanter is not None:
                q = layer.weight_quanter(layer.weight)
                layer.weight._in_place_update(q._value)
                layer.weight_quanter = None
        return model


class EMAObserver:
    """Moving-average absmax calibration (reference
    FakeQuanterWithAbsMaxObserver's EMA, observe-only): scale tracks
    ema <- rate*ema + (1-rate)*absmax(batch)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._ema = None

    def observe(self, x):
        import jax.numpy as jnp
        v = x._value if hasattr(x, "_value") else jnp.asarray(x)
        m = float(jnp.max(jnp.abs(v)))
        self._ema = m if self._ema is None else \
            self.moving_rate * self._ema + (1 - self.moving_rate) * m
        return x

    def scale(self):
        # convention: scale == clip RANGE (absmax), as in AbsmaxObserver
        return self._ema or 1e-9

    def _instance(self, layer):
        import copy
        return copy.deepcopy(self)


class PercentileObserver:
    """Percentile calibration (reference KL/hist observers' purpose:
    clip activation outliers instead of letting one spike set the
    absmax scale). Keeps a bounded reservoir of |x| samples and uses
    the q-th percentile as the clipping range."""

    def __init__(self, quant_bits=8, percentile=99.9, max_samples=1 << 16):
        self.quant_bits = quant_bits
        self.percentile = percentile
        self.max_samples = max_samples
        self._samples = []
        self._count = 0

    def observe(self, x):
        import numpy as np
        v = np.abs(np.asarray(x._value if hasattr(x, "_value") else x)
                   ).reshape(-1)
        if v.size > 4096:                      # bound per-batch cost
            idx = np.random.default_rng(self._count).choice(
                v.size, 4096, replace=False)
            v = v[idx]
        self._count += 1
        self._samples.append(v)
        total = sum(s.size for s in self._samples)
        while total > self.max_samples and len(self._samples) > 1:
            total -= self._samples.pop(0).size
        return x

    def scale(self):
        import numpy as np
        if not self._samples:
            return 1e-9
        allv = np.concatenate(self._samples)
        # convention: scale == clip RANGE (absmax), as in AbsmaxObserver
        return max(float(np.percentile(allv, self.percentile)), 1e-9)

    def _instance(self, layer):
        import copy
        return copy.deepcopy(self)


class _CalibrationQuanter:
    """Observe-only during calibration; fake-quant with the FROZEN scale
    after freeze() (PTQ semantics: calibration must see the raw float
    activations, reference ptq.py)."""

    def __init__(self, observer):
        self.observer = observer
        self.frozen_scale = None
        self.disabled = False

    def __call__(self, x):
        if self.disabled:
            return x
        if self.frozen_scale is None:
            return self.observer.observe(x)
        return _fake_quant_t(x, self.frozen_scale,
                             self.observer.quant_bits)

    def freeze(self):
        scale = self.observer.scale()
        if scale <= 2e-9:
            # never observed (layer not exercised during calibration):
            # quantizing with a degenerate scale would clamp activations
            # to ~0 — pass through instead and tell the user
            import warnings
            warnings.warn(
                "PTQ convert: an activation observer collected no "
                "calibration data (layer never ran during calibrate()); "
                "leaving that layer's activations UN-quantized",
                RuntimeWarning, stacklevel=3)
            self.disabled = True
            return
        self.frozen_scale = scale


def _fake_quant_t(x, scale, bits):
    from ..core.dispatch import apply_op
    return apply_op("fake_quant",
                    lambda v: _fake_quant(v, scale, bits), (x,), {})


class PTQ:
    """reference ptq.py PTQ — observe activations on calibration data,
    then convert with fixed scales. Workflow:

        q = PTQ(QuantConfig(activation=PercentileObserver(), weight=...))
        m = q.quantize(model)
        q.calibrate(m, calib_batches)   # raw float forwards, observers see
        m = q.convert(m)                # freeze scales + bake weights
    """

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        model = _wrap_layers(model, self.config)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear):
                # weights: scale fixed immediately (data-independent)
                if layer.weight_quanter is not None:
                    layer.weight_quanter(layer.weight)
                # activations: observe-only until convert()
                aq = layer.activation_quanter
                if aq is not None and hasattr(aq, "observe"):
                    layer.activation_quanter = _CalibrationQuanter(aq)
        return model

    def calibrate(self, model, data, steps=None):
        """Run calibration forwards (no quantization applied yet); the
        activation observers collect ranges."""
        from ..core import autograd
        with autograd.no_grad():
            for i, batch in enumerate(data):
                xs = batch if isinstance(batch, (list, tuple)) else [batch]
                model(*xs)
                if steps is not None and i + 1 >= steps:
                    break
        return model

    def convert(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear):
                aq = layer.activation_quanter
                if isinstance(aq, _CalibrationQuanter):
                    aq.freeze()                # fixed scales from here on
                if layer.weight_quanter is not None:
                    q = layer.weight_quanter(layer.weight)
                    layer.weight._in_place_update(q._value)
                    layer.weight_quanter = None
        return model
